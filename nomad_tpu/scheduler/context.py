"""Eval context: per-evaluation caches and plan-aware state access
(ref scheduler/context.go).

The critical piece is `proposed_allocs` (context.go:120): the scheduler sees
state allocs MINUS in-plan stops/preemptions PLUS in-plan placements, so that
multiple placements within one eval account for each other — and so the TPU
solver's running-usage updates match (SURVEY.md hard part 1).
"""
from __future__ import annotations

import re
from typing import Optional

from ..structs import (
    Allocation, AllocMetric, Plan, SchedulerConfiguration, Node,
)


class EvalCache:
    """Per-eval regexp/version-constraint caches (ref context.go EvalCache)."""

    def __init__(self):
        self.regexp: dict[str, re.Pattern] = {}
        self.version_constraint: dict[str, object] = {}
        self.semver_constraint: dict[str, object] = {}


# Feasibility-cache verdicts (ref context.go ComputedClassFeasibility)
EVAL_COMPUTED_CLASS_UNKNOWN = 0
EVAL_COMPUTED_CLASS_IGNORE = 1
EVAL_COMPUTED_CLASS_ELIGIBLE = 2
EVAL_COMPUTED_CLASS_INELIGIBLE = 3
EVAL_COMPUTED_CLASS_ESCAPED = 4


class EvalEligibility:
    """Tracks feasibility per computed node class so constraint checks run
    once per *class*, not once per node (ref context.go:190).

    Constraints referencing unique.* attributes "escape" the class system and
    must be checked per node."""

    def __init__(self):
        self.job: dict[str, int] = {}          # class -> verdict
        self.job_escaped = False
        self.tg: dict[str, dict[str, int]] = {}  # tg -> class -> verdict
        self.tg_escaped: dict[str, bool] = {}
        self.quota_reached: str = ""

    def set_job(self, job) -> None:
        self.job_escaped = _constraints_escape(job.constraints)
        for tg in job.task_groups:
            esc = _constraints_escape(tg.constraints)
            if not esc:
                for task in tg.tasks:
                    if _constraints_escape(task.constraints):
                        esc = True
                        break
            self.tg_escaped[tg.name] = esc

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def job_status(self, klass: str) -> int:
        if self.job_escaped:
            return EVAL_COMPUTED_CLASS_ESCAPED
        if not klass:
            return EVAL_COMPUTED_CLASS_IGNORE
        return self.job.get(klass, EVAL_COMPUTED_CLASS_UNKNOWN)

    def set_job_eligibility(self, eligible: bool, klass: str) -> None:
        if klass:
            self.job[klass] = (EVAL_COMPUTED_CLASS_ELIGIBLE if eligible
                               else EVAL_COMPUTED_CLASS_INELIGIBLE)

    def task_group_status(self, tg: str, klass: str) -> int:
        if self.tg_escaped.get(tg):
            return EVAL_COMPUTED_CLASS_ESCAPED
        if not klass:
            return EVAL_COMPUTED_CLASS_IGNORE
        return self.tg.get(tg, {}).get(klass, EVAL_COMPUTED_CLASS_UNKNOWN)

    def set_task_group_eligibility(self, eligible: bool, tg: str, klass: str) -> None:
        if klass:
            self.tg.setdefault(tg, {})[klass] = (
                EVAL_COMPUTED_CLASS_ELIGIBLE if eligible
                else EVAL_COMPUTED_CLASS_INELIGIBLE)

    def get_classes(self) -> dict[str, bool]:
        """Roll up eligibility per class for blocked-eval unblock hints."""
        out: dict[str, bool] = {}
        for klass, v in self.job.items():
            out[klass] = (v == EVAL_COMPUTED_CLASS_ELIGIBLE)
        for tg_map in self.tg.values():
            for klass, v in tg_map.items():
                if v == EVAL_COMPUTED_CLASS_ELIGIBLE:
                    out[klass] = True
                elif klass not in out:
                    out[klass] = False
        return out


def _constraints_escape(constraints) -> bool:
    for c in constraints:
        for target in (c.ltarget, c.rtarget):
            if "${unique." in target or "${node.unique." in target or \
               "${attr.unique." in target or "${meta.unique." in target:
                return True
    return False


class EvalContext:
    """Holds everything one evaluation's scheduling needs (ref context.go
    EvalContext)."""

    def __init__(self, state, plan: Optional[Plan] = None, logger=None):
        self.state = state                  # StateSnapshot (scheduler State iface)
        self.plan = plan
        self.logger = logger
        self.cache = EvalCache()
        self.eligibility = EvalEligibility()
        self.metrics = AllocMetric()
        self.scheduler_config: SchedulerConfiguration = (
            state.get_scheduler_config() if state is not None
            else SchedulerConfiguration())

    def reset_metrics(self) -> AllocMetric:
        m = self.metrics
        self.metrics = AllocMetric()
        return m

    def regexp(self, pattern: str) -> Optional[re.Pattern]:
        r = self.cache.regexp.get(pattern)
        if r is None:
            try:
                r = re.compile(pattern)
            except re.error:
                return None
            self.cache.regexp[pattern] = r
        return r

    def proposed_allocs(self, node_id: str) -> list[Allocation]:
        """State allocs − plan stops/preemptions + plan placements
        (ref context.go:120 ProposedAllocs)."""
        existing = [a for a in self.state.allocs_by_node(node_id)
                    if not a.terminal_status()]
        if self.plan is None:
            return existing
        remove_ids = {a.id for a in self.plan.node_update.get(node_id, ())}
        remove_ids |= {a.id for a in self.plan.node_preemptions.get(node_id, ())}
        proposed = [a for a in existing if a.id not in remove_ids]
        # plan placements replace same-id allocs (in-place updates)
        placed = self.plan.node_allocation.get(node_id, [])
        placed_ids = {a.id for a in placed}
        proposed = [a for a in proposed if a.id not in placed_ids]
        proposed.extend(placed)
        return proposed
