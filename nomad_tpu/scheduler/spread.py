"""Spread scoring iterator (ref scheduler/spread.go): targeted percentages or
even-spread boosts over a property dimension.
"""
from __future__ import annotations

from typing import Optional

from ..structs import TaskGroup
from .context import EvalContext
from .feasible import resolve_target
from .propertyset import PropertySet
from .rank import RankedNode, RankIterator

IMPLICIT_TARGET = "*"


class SpreadIterator(RankIterator):
    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.job = None
        self.tg: Optional[TaskGroup] = None
        self.job_spreads = []
        self.group_property_sets: dict[str, list[PropertySet]] = {}
        # tg -> (attribute -> (weight, desired counts), weight sum)
        self.tg_spread_info: dict[
            str, tuple[dict[str, tuple[int, dict[str, float]]], int]] = {}
        self.has_spread = False

    def set_job(self, job) -> None:
        self.job = job
        self.job_spreads = list(job.spreads)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets = []
            for spread in self.job_spreads + list(tg.spreads):
                ps = PropertySet(self.ctx, self.job)
                ps.set_target_attribute(spread.attribute, tg.name)
                sets.append(ps)
            self.group_property_sets[tg.name] = sets
        self.has_spread = bool(self.group_property_sets[tg.name])
        if tg.name not in self.tg_spread_info:
            self._compute_spread_info(tg)

    def _compute_spread_info(self, tg: TaskGroup) -> None:
        infos: dict[str, tuple[int, dict[str, float]]] = {}
        total = tg.count
        sum_weights = 0
        for spread in list(tg.spreads) + self.job_spreads:
            desired: dict[str, float] = {}
            sum_desired = 0.0
            for st in spread.spread_target:
                d = (st.percent / 100.0) * total
                desired[st.value] = d
                sum_desired += d
            if 0 < sum_desired < total:
                desired[IMPLICIT_TARGET] = total - sum_desired
            infos[spread.attribute] = (spread.weight, desired)
            sum_weights += spread.weight
        self.tg_spread_info[tg.name] = (infos, sum_weights)

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not self.has_spread:
            return option
        tg_name = self.tg.name
        infos, sum_weights = self.tg_spread_info[tg_name]
        total_score = 0.0
        for ps in self.group_property_sets[tg_name]:
            val, ok = resolve_target(ps.target_attribute, option.node)
            used = ps.used_counts()
            used_count = used.get(str(val), 0) if ok and val is not None else 0
            used_count += 1  # include this prospective placement
            if not ok or val is None:
                total_score -= 1.0
                continue
            weight, desired = infos.get(ps.target_attribute, (0, {}))
            if not desired:
                total_score += _even_spread_boost(ps, str(val))
            else:
                d = desired.get(str(val), desired.get(IMPLICIT_TARGET))
                if d is None:
                    total_score -= 1.0
                    continue
                spread_weight = weight / sum_weights if sum_weights else 0.0
                total_score += ((d - used_count) / d) * spread_weight
        if total_score != 0.0:
            option.scores.append(total_score)
            self.ctx.metrics.score_node(option.node.id, "allocation-spread",
                                        total_score)
        return option

    def reset(self) -> None:
        self.source.reset()
        # property sets see fresh plan deltas on every select


def _even_spread_boost(ps: PropertySet, value: str) -> float:
    """Even spread when no targets are given (ref spread.go:178
    evenSpreadScoreBoost)."""
    combined = ps.used_counts()
    if not combined:
        return 0.0
    current = combined.get(value, 0)
    counts = list(combined.values())
    min_count = min(counts)
    max_count = max(counts)
    if current != min_count:
        if min_count == 0:
            return -1.0
        return float(min_count - current) / float(min_count)
    if min_count == max_count:
        return -1.0
    if min_count == 0:
        return 1.0
    return float(max_count - min_count) / float(min_count)
