"""Placement stacks: the iterator pipelines assembled per scheduler type
(ref scheduler/stack.go:43 GenericStack, :190 SystemStack, :343
NewGenericStack).
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Optional

from ..structs import Job, Node, TaskGroup
from .context import EvalContext
from .feasible import (
    ConstraintChecker, CSIVolumeChecker, DeviceChecker, DistinctHostsIterator,
    DistinctPropertyIterator, DriverChecker, FeasibilityWrapper,
    HostVolumeChecker, NetworkChecker, StaticIterator,
)
from .rank import (
    BinPackIterator, FeasibleRankIterator, JobAntiAffinityIterator,
    NodeAffinityIterator, NodeReschedulingPenaltyIterator,
    PreemptionScoringIterator, RankedNode, ScoreNormalizationIterator,
)
from .select import LimitIterator, MaxScoreIterator
from .spread import SpreadIterator


@dataclasses.dataclass
class SelectOptions:
    """ref stack.go SelectOptions"""
    penalty_node_ids: set[str] = dataclasses.field(default_factory=set)
    preferred_nodes: list[Node] = dataclasses.field(default_factory=list)
    preempt: bool = False
    alloc_name: str = ""


def _task_group_constraints(tg: TaskGroup):
    """Collect drivers + constraints from the TG and its tasks
    (ref stack.go taskGroupConstraints)."""
    constraints = list(tg.constraints)
    drivers: set[str] = set()
    for task in tg.tasks:
        if task.driver:
            drivers.add(task.driver)
        constraints.extend(task.constraints)
    return drivers, constraints


class GenericStack:
    """ref stack.go:43"""

    def __init__(self, batch: bool, ctx: EvalContext,
                 rng: Optional[random.Random] = None):
        self.batch = batch
        self.ctx = ctx
        # the scheduler passes a per-eval seeded Random (seeded from the
        # eval id) so identical (snapshot, eval, seed) inputs reproduce
        # bit-identical placements; the bare default is deterministic too
        # rather than OS-entropy-seeded (DET001)
        self.rng = rng if rng is not None else random.Random(0)
        self.job_version: Optional[int] = None

        self.source = StaticIterator(ctx, [])
        self.job_constraint = ConstraintChecker(ctx, [])
        self.tg_drivers = DriverChecker(ctx)
        self.tg_constraint = ConstraintChecker(ctx, [])
        self.tg_devices = DeviceChecker(ctx)
        self.tg_host_volumes = HostVolumeChecker(ctx)
        self.tg_csi_volumes = CSIVolumeChecker(ctx)
        self.job_namespace = "default"
        self.job_id = ""
        self.tg_network = NetworkChecker(ctx)

        self.wrapped_checks = FeasibilityWrapper(
            ctx, self.source,
            job_checks=[self.job_constraint],
            tg_checks=[self.tg_drivers, self.tg_constraint,
                       self.tg_host_volumes, self.tg_devices,
                       self.tg_network],
            tg_available=[self.tg_csi_volumes])
        self.distinct_hosts = DistinctHostsIterator(ctx, self.wrapped_checks)
        self.distinct_property = DistinctPropertyIterator(
            ctx, self.distinct_hosts)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property)
        self.bin_pack = BinPackIterator(
            ctx, rank_source, evict=False, priority=0,
            algorithm=ctx.scheduler_config.effective_scheduler_algorithm())
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.bin_pack)
        self.node_resched_penalty = NodeReschedulingPenaltyIterator(
            ctx, self.job_anti_aff)
        self.node_affinity = NodeAffinityIterator(
            ctx, self.node_resched_penalty)
        self.spread = SpreadIterator(ctx, self.node_affinity)
        preemption_scorer = PreemptionScoringIterator(ctx, self.spread)
        self.score_norm = ScoreNormalizationIterator(ctx, preemption_scorer)
        self.limit = LimitIterator(ctx, self.score_norm, 2)
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, nodes: list[Node]) -> None:
        """Shuffle + log2 limit (power-of-two-choices for batch)
        (ref stack.go:71-91)."""
        nodes = list(nodes)
        self.rng.shuffle(nodes)
        self.source.set_nodes(nodes)
        limit = 2
        n = len(nodes)
        if not self.batch and n > 0:
            limit = max(limit, int(math.ceil(math.log2(n))))
        self.limit.set_limit(limit)

    def set_job(self, job: Job) -> None:
        if self.job_version is not None and self.job_version == job.version:
            return
        self.job_version = job.version
        self.job_namespace = job.namespace
        self.job_id = job.id
        self.job_constraint.set_constraints(list(job.constraints))
        self.distinct_hosts.set_job(job)
        self.distinct_property.set_job(job)
        self.bin_pack.set_job(job)
        self.job_anti_aff.set_job(job)
        self.node_affinity.set_job(job)
        self.spread.set_job(job)
        self.ctx.eligibility.set_job(job)

    def select(self, tg: TaskGroup,
               options: Optional[SelectOptions] = None) -> Optional[RankedNode]:
        options = options or SelectOptions()

        if options.preferred_nodes:
            original = self.source.nodes
            self.source.set_nodes(options.preferred_nodes)
            sub = dataclasses.replace(options, preferred_nodes=[])
            option = self.select(tg, sub)
            self.source.set_nodes(original)
            if option is not None:
                return option
            return self.select(tg, sub)

        self.max_score.reset()
        self.ctx.reset_metrics()

        drivers, constraints = _task_group_constraints(tg)
        self.tg_drivers.set_drivers(drivers)
        self.tg_constraint.set_constraints(constraints)
        self.tg_devices.set_task_group(tg)
        self.tg_host_volumes.set_volumes(options.alloc_name, tg.volumes)
        self.tg_csi_volumes.set_volumes(tg.volumes, self.job_namespace,
                                        job_id=self.job_id)
        self.tg_network.set_network(tg.networks[0] if tg.networks else None)
        self.distinct_hosts.set_task_group(tg)
        self.distinct_property.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)
        self.bin_pack.evict = options.preempt
        self.job_anti_aff.set_task_group(tg)
        self.node_resched_penalty.set_penalty_nodes(options.penalty_node_ids)
        self.node_affinity.set_task_group(tg)
        self.spread.set_task_group(tg)

        if self.node_affinity.has_affinities() or self.spread.has_spread:
            # spread/affinity scoring needs a wider sample (ref stack.go:165)
            self.limit.set_limit(max(tg.count, 100))

        return self.max_score.next()


class SystemStack:
    """Stack for system/sysbatch jobs: every feasible node, no shuffle/limit
    (ref stack.go:190)."""

    def __init__(self, ctx: EvalContext, sysbatch: bool = False):
        self.ctx = ctx
        self.source = StaticIterator(ctx, [])
        self.job_constraint = ConstraintChecker(ctx, [])
        self.tg_drivers = DriverChecker(ctx)
        self.tg_constraint = ConstraintChecker(ctx, [])
        self.tg_devices = DeviceChecker(ctx)
        self.tg_host_volumes = HostVolumeChecker(ctx)
        self.tg_csi_volumes = CSIVolumeChecker(ctx)
        self.job_namespace = "default"
        self.job_id = ""
        self.tg_network = NetworkChecker(ctx)
        self.wrapped_checks = FeasibilityWrapper(
            ctx, self.source,
            job_checks=[self.job_constraint],
            tg_checks=[self.tg_drivers, self.tg_constraint,
                       self.tg_host_volumes, self.tg_devices,
                       self.tg_network],
            tg_available=[self.tg_csi_volumes])
        self.distinct_property = DistinctPropertyIterator(
            ctx, self.wrapped_checks)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property)
        self.bin_pack = BinPackIterator(
            ctx, rank_source, evict=False, priority=0,
            algorithm=ctx.scheduler_config.effective_scheduler_algorithm())
        self.score_norm = ScoreNormalizationIterator(ctx, self.bin_pack)

    def set_nodes(self, nodes: list[Node]) -> None:
        self.source.set_nodes(nodes)

    def set_job(self, job: Job) -> None:
        self.job_namespace = job.namespace
        self.job_id = job.id
        self.job_constraint.set_constraints(list(job.constraints))
        self.distinct_property.set_job(job)
        self.bin_pack.set_job(job)
        self.ctx.eligibility.set_job(job)

    def select(self, tg: TaskGroup,
               options: Optional[SelectOptions] = None) -> Optional[RankedNode]:
        options = options or SelectOptions()
        self.score_norm.reset()
        drivers, constraints = _task_group_constraints(tg)
        self.tg_drivers.set_drivers(drivers)
        self.tg_constraint.set_constraints(constraints)
        self.tg_devices.set_task_group(tg)
        self.tg_host_volumes.set_volumes(options.alloc_name, tg.volumes)
        self.tg_csi_volumes.set_volumes(tg.volumes, self.job_namespace,
                                        job_id=self.job_id)
        self.tg_network.set_network(tg.networks[0] if tg.networks else None)
        self.distinct_property.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)
        self.bin_pack.evict = options.preempt
        return self.score_norm.next()
