"""Scheduler utilities (ref scheduler/util.go): tainted nodes, task-updated
detection, in-place vs destructive update classification.
"""
from __future__ import annotations

from typing import Optional

from ..structs import (
    Allocation, AllocatedResources, AllocatedTaskResources, Job, Node,
    TaskGroup, ALLOC_CLIENT_LOST, ALLOC_CLIENT_RUNNING,
    ALLOC_CLIENT_UNKNOWN, ALLOC_DESIRED_STOP, DESC_NODE_TAINTED,
)


def tainted_nodes(state, allocs: list[Allocation]) -> dict[str, Optional[Node]]:
    """Map of node_id -> Node for nodes that are tainted (down, draining,
    disconnected or GC'd) among the allocs' nodes (ref util.go taintedNodes).
    GC'd nodes map to None."""
    out: dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.terminal_status() or node.drain or \
           node.scheduling_eligibility == "ineligible":
            out[alloc.node_id] = node
    return out


def ready_nodes_in_dcs(state, datacenters: list[str]
                       ) -> tuple[list[Node], dict[str, int]]:
    """Ready nodes in the given DCs plus per-DC availability counts
    (ref util.go readyNodesInDCs)."""
    ready = []
    by_dc: dict[str, int] = {}
    dcs = set(datacenters)
    for node in state.iter_nodes():
        if not node.ready():
            continue
        if node.datacenter not in dcs:
            continue
        ready.append(node)
        by_dc[node.datacenter] = by_dc.get(node.datacenter, 0) + 1
    return ready, by_dc


def retry_max(max_attempts: int, fn, reset_fn=None) -> bool:
    """Retry fn up to max attempts; reset_fn() True resets the counter
    (ref util.go retryMax)."""
    attempts = 0
    while attempts < max_attempts:
        if fn():
            return True
        if reset_fn is not None and reset_fn():
            attempts = 0
        else:
            attempts += 1
    return False


def progress_made(result) -> bool:
    """Did the plan application make any progress? (ref util.go progressMade)"""
    return result is not None and (
        result.node_update or result.node_allocation or
        result.deployment is not None or result.deployment_updates)


def tasks_updated(job_a: Job, job_b: Job, group: str) -> bool:
    """Would moving from job_a to job_b for this group require a destructive
    update? (ref util.go tasksUpdated) — any change to driver/config/env/
    resources/networks/volumes etc."""
    a = job_a.lookup_task_group(group)
    b = job_b.lookup_task_group(group)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if _networks_updated(a.networks, b.networks):
        return True
    if a.volumes != b.volumes:
        return True
    if a.ephemeral_disk != b.ephemeral_disk:
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver or at.user != bt.user:
            return True
        if at.config != bt.config or at.env != bt.env:
            return True
        if at.meta != bt.meta:
            return True
        if at.artifacts != bt.artifacts or at.templates != bt.templates:
            return True
        if at.volume_mounts != bt.volume_mounts:
            return True
        ar, br = at.resources, bt.resources
        if (ar.cpu, ar.cores, ar.memory_mb, ar.memory_max_mb) != \
           (br.cpu, br.cores, br.memory_mb, br.memory_max_mb):
            return True
        if _networks_updated(ar.networks, br.networks):
            return True
        if [d.name for d in ar.devices] != [d.name for d in br.devices] or \
           [d.count for d in ar.devices] != [d.count for d in br.devices]:
            return True
        if at.lifecycle != bt.lifecycle:
            return True
    return False


def _networks_updated(a, b) -> bool:
    if len(a) != len(b):
        return True
    for na, nb in zip(a, b):
        if na.mode != nb.mode or na.mbits != nb.mbits:
            return True
        if [(p.label, p.value, p.to) for p in na.reserved_ports] != \
           [(p.label, p.value, p.to) for p in nb.reserved_ports]:
            return True
        if [(p.label, p.to) for p in na.dynamic_ports] != \
           [(p.label, p.to) for p in nb.dynamic_ports]:
            return True
    return False


def generic_alloc_update_fn(ctx, eval_obj, job: Job):
    """Returns fn(alloc, new_job, tg) -> (ignore, destructive, inplace_alloc)
    (ref util.go genericAllocUpdateFn)."""

    def update_fn(existing: Allocation, new_job: Job, new_tg: TaskGroup):
        # Same job definition => ignore
        if existing.job is not None and \
           existing.job.version == new_job.version and \
           existing.job.create_index == new_job.create_index:
            return True, False, None

        # Task-level changes => destructive
        if existing.job is not None and \
           tasks_updated(existing.job, new_job, new_tg.name):
            return False, True, None

        # In-place candidate: re-check the node still fits with the updated
        # (count-insensitive) definition
        node = ctx.state.node_by_id(existing.node_id)
        if node is None:
            return False, True, None

        proposed = [a for a in ctx.proposed_allocs(existing.node_id)
                    if a.id != existing.id]
        from ..structs import allocs_fit
        new_alloc = existing.copy()
        new_alloc.job = None  # normalized to plan job on append
        fit, _, _ = allocs_fit(node, proposed + [new_alloc])
        if not fit:
            return False, True, None
        return False, False, new_alloc

    return update_fn


def update_non_terminal_allocs_to_lost(plan, tainted: dict[str, Optional[Node]],
                                       allocs: list[Allocation],
                                       job=None, now: float = 0.0) -> None:
    """Mark non-terminal allocs on down nodes as lost in the plan
    (ref generic_sched.go:350 updateNonTerminalAllocsToLost via util).

    Disconnect-eligible allocs (group sets max_client_disconnect and the
    window hasn't expired) are skipped — the reconciler rides them out
    as `unknown` instead; stopping them here would race the attribute
    update in the same plan (ref Nomad gates this on
    supportsDisconnectedClients). `now` is the eval's clock — callers
    pass the same timestamp the reconciler uses so both ends of the
    disconnect window agree (0 falls back to wall clock)."""
    import time as _time
    # callers inject the eval clock; bare wall clock is the documented
    # fallback contract above
    now = now or _time.time()   # nomadlint: disable=DET001 — spec fallback
    for alloc in allocs:
        node = tainted.get(alloc.node_id, "absent")
        if node == "absent":
            continue
        if node is not None and not node.terminal_status():
            continue  # only down/GC'd nodes strand allocs as lost
        if alloc.terminal_status():
            continue
        tg = job.lookup_task_group(alloc.task_group) if job else None
        window = getattr(tg, "max_client_disconnect_sec", None) if tg \
            else None
        if window and alloc.client_status in (ALLOC_CLIENT_RUNNING,
                                              ALLOC_CLIENT_UNKNOWN):
            since = alloc.disconnected_at or now
            if now < since + window:
                continue          # the reconciler handles the window
        plan.append_stopped_alloc(alloc, DESC_NODE_TAINTED,
                                  client_status=ALLOC_CLIENT_LOST)
