"""Generic scheduler for service and batch jobs
(ref scheduler/generic_sched.go).

Process(eval) -> plan(s) submitted through the Planner interface. The
placement loop delegates to the GenericStack (CPU oracle) or to the TPU
batched solver when SchedulerConfiguration.scheduler_algorithm == "tpu-batch"
(the SURVEY.md north star: same reconciler, same plan semantics, batched
scoring).
"""
from __future__ import annotations

import random
import time
from typing import Optional

from ..structs import (
    AllocatedResources, Allocation,
    AllocDeploymentStatus, Evaluation, Job, Plan, PlanAnnotations,
    DesiredUpdates, DESC_CANARY, DESC_NODE_TAINTED,
    EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED, JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE, TRIGGER_MAX_PLANS, TRIGGER_PREEMPTION,
    TRIGGER_RETRY_FAILED_ALLOC, new_id, SCHED_ALG_CONVEX, SCHED_ALG_TPU,
    skeleton_for,
)
from ..metrics import metrics
from ..obs import trace
from .context import EvalContext
from .reconcile import AllocReconciler, AllocPlaceResult
from .stack import GenericStack, SelectOptions
from .util import (
    generic_alloc_update_fn, ready_nodes_in_dcs, tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5   # ref generic_sched.go:18
MAX_BATCH_SCHEDULE_ATTEMPTS = 2     # ref generic_sched.go:22

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS_DESC = "created to place remaining allocations"


class SetStatusError(Exception):
    def __init__(self, err: str, eval_status: str):
        super().__init__(err)
        self.eval_status = eval_status


class GenericScheduler:
    """ref generic_sched.go:58"""

    def __init__(self, state, planner, batch: bool, logger=None):
        self.state = state          # snapshot (scheduler State interface)
        self.planner = planner      # Planner interface
        self.batch = batch
        self.logger = logger

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None
        self.solver = None          # TPU batch solver, created lazily

        self.deployment = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: dict[str, object] = {}
        # per-TG explain records from the tensor solve (ISSUE 11): the
        # placer registers one per solved task group so a failed
        # placement attaches the device-derived AllocMetric
        self.solver_explains: dict[str, object] = {}
        self.queued_allocs: dict[str, int] = {}
        self.followup_evals: dict[str, list[Evaluation]] = {}
        # set by the pipelined placer when an intermediate chunk plan
        # under-committed (optimistic-concurrency rejection mid-pipeline):
        # the pass must refresh state and retry, the same contract as a
        # partial commit of a serial plan
        self._pipeline_partial = False
        # per-scheduler ResourceSkeleton pool (structs/respool.py): the
        # host placement path shares each TG's immutable disk-only row
        # instead of minting one per allocation
        self._skel: dict = {}

    # ------------------------------------------------------------- process

    def process(self, eval: Evaluation) -> None:
        """ref generic_sched.go:125 Process"""
        self.eval = eval
        limit = (MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch
                 else MAX_SERVICE_SCHEDULE_ATTEMPTS)
        try:
            success = self._retry_max(limit, self._process)
        except SetStatusError as e:
            self._set_status(e.eval_status, str(e))
            return
        if not success:
            # exceeded plan attempts: requeue as blocked
            blocked = eval.create_blocked_eval({}, True, "", self.failed_tg_allocs)
            blocked.triggered_by = TRIGGER_MAX_PLANS
            blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
            self.planner.create_eval(blocked)
            self._set_status(EVAL_STATUS_FAILED, "maximum attempts reached")
            return
        self._set_status(EVAL_STATUS_COMPLETE, "")

    def _retry_max(self, limit: int, fn) -> bool:
        attempts = 0
        while attempts < limit:
            if fn():
                return True
            attempts += 1
            # refresh state to latest on retry (ref worker RefreshIndex)
            self.state = self.planner.refresh_snapshot(self.state)
        return False

    def _process(self) -> bool:
        """One scheduling pass; returns True when done (ref
        generic_sched.go:216 process)."""
        eval = self.eval
        self.job = self.state.job_by_id(eval.namespace, eval.job_id)

        self._pipeline_partial = False
        self.failed_tg_allocs = {}
        self.solver_explains = {}
        self.queued_allocs = {tg.name: 0 for tg in
                              (self.job.task_groups if self.job else [])}
        self.plan = eval.make_plan(self.job)
        self.plan.snapshot_index = self.state.latest_index()

        if not self.batch:
            self.deployment = self.state.latest_deployment_by_job(
                eval.namespace, eval.job_id)
            if self.deployment is not None and not self.deployment.active():
                self.deployment = None

        self.ctx = EvalContext(self.state, self.plan, self.logger)
        # per-eval seeded rng (DET001): the stack's shuffle and the TPU
        # placer's permutation/jitter all draw from this stream, so one
        # (snapshot, eval) replays bit-identically while concurrent
        # workers (distinct eval ids) still decorrelate. str seeds hash
        # via sha512 — stable across processes, unlike hash().
        self.stack = GenericStack(self.batch, self.ctx,
                                  rng=random.Random(eval.id))
        if self.job and not self.job.stopped():
            ready, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
            self.ctx.metrics.nodes_available = by_dc
            self._nodes_by_dc = by_dc
            self.stack.set_nodes(ready)
            self.stack.set_job(self.job)
            self._ready_nodes = ready
        else:
            self._ready_nodes = []
            self._nodes_by_dc = {}

        # compute the changes
        if not self._compute_job_allocs():
            return False

        # if any placements failed, create/update a blocked eval
        if self.failed_tg_allocs and self.blocked is None:
            self.blocked = eval.create_blocked_eval(
                self.ctx.eligibility.get_classes(),
                self.ctx.eligibility.has_escaped(),
                self.ctx.eligibility.quota_reached,
                self.failed_tg_allocs)
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS_DESC
            self.planner.create_eval(self.blocked)

        # create follow-up evals for delayed reschedules
        for evals in self.followup_evals.values():
            for ev in evals:
                self.planner.create_eval(ev)

        eval.queued_allocations = dict(self.queued_allocs)

        if self.plan.is_no_op():
            # an intermediate pipelined chunk may have under-committed even
            # when the FINAL plan carries nothing: refresh and retry, the
            # same contract as a partial commit of a serial plan
            return not self._pipeline_partial

        if self.plan.annotations is not None:
            # resolved now that placement filled the plan (ref
            # structs.go PlanAnnotations.PreemptedAllocs)
            self.plan.annotations.preempted_allocs = [
                a.id for allocs in self.plan.node_preemptions.values()
                for a in allocs]
        result = self.planner.submit_plan(self.plan)
        self.plan_result = result
        if result is None:
            return False

        # partial application handling (ref generic_sched.go:317)
        full, expected, actual = result.full_commit(self.plan)
        if not full:
            if result.is_no_op():
                return False
            # progress was made; retry for the rest
            return False
        # the final plan committed fully, but a pipelined intermediate
        # chunk may have been rejected by the applier's latest-state
        # re-check: those placements never landed, so refresh and retry
        # exactly as a serial partial commit would
        return not self._pipeline_partial

    # ----------------------------------------------------- compute allocs

    def _compute_job_allocs(self) -> bool:
        """ref generic_sched.go:332 computeJobAllocs"""
        eval = self.eval
        allocs = self.state.allocs_by_job(eval.namespace, eval.job_id)
        tainted = tainted_nodes(self.state, allocs)

        # reschedule/disconnect windows are wall-clock by SPEC (the
        # reference compares against real time everywhere)
        now = time.time()   # nomadlint: disable=DET001 — spec wall clock
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs,
                                           job=self.job, now=now)

        update_fn = generic_alloc_update_fn(self.ctx, eval, self.job)
        reconciler = AllocReconciler(
            alloc_update_fn=update_fn,
            batch=self.batch,
            job_id=eval.job_id,
            job=self.job,
            deployment=self.deployment,
            existing_allocs=allocs,
            tainted_nodes=tainted,
            eval_id=eval.id,
            eval_priority=eval.priority,
            now=now)
        with metrics.measure("nomad.scheduler.reconcile"), \
                trace.span("scheduler.reconcile"):
            results = reconciler.compute()
        self.followup_evals = results.desired_followup_evals

        if eval.annotate_plan:
            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=results.desired_tg_updates)

        # add stops to the plan
        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status,
                stop.follow_up_eval_id)

        # attribute updates (follow-up eval id markers)
        for alloc in results.attribute_updates.values():
            self.plan.append_alloc(alloc, None)

        # in-place updates
        for alloc in results.inplace_update:
            self.plan.append_alloc(alloc, None)

        # deployment changes
        if results.deployment is not None:
            self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        # queued allocations per tg
        for tg_name, du in results.desired_tg_updates.items():
            self.queued_allocs[tg_name] = self.queued_allocs.get(tg_name, 0) + \
                du.place + du.destructive_update

        # nothing to place?
        destructive = results.destructive_update
        place = results.place
        if not place and not destructive:
            return True

        return self._compute_placements(destructive, place)

    def _compute_placements(self, destructive, place) -> bool:
        """Place missing allocations (ref generic_sched.go:472
        computePlacements). Delegates to the TPU solver when configured."""
        algorithm = self.ctx.scheduler_config.effective_scheduler_algorithm()
        if algorithm in (SCHED_ALG_TPU, SCHED_ALG_CONVEX):
            # the convex algorithm rides the same tensor placer; its
            # solves route through the convex tier (backend.select_convex)
            # and demote to the identical greedy ladder on any failure
            try:
                from ..solver import SolverPlacer
            except ImportError:
                pass  # solver unavailable: fall back to the generic stack
            else:
                placer = SolverPlacer(self)
                return placer.compute_placements(destructive, place)

        deployment_id = ""
        if self.deployment is not None and self.deployment.active():
            deployment_id = self.deployment.id
        if self.plan.deployment is not None:
            deployment_id = self.plan.deployment.id

        # byDC availability metrics are set already; iterate placements
        for missing in list(destructive) + list(place):
            if isinstance(missing, AllocPlaceResult):
                tg = missing.task_group
                name = missing.name
                prev = missing.previous_alloc
                is_destructive = False
            else:
                tg = missing.place_task_group
                name = missing.place_name
                prev = missing.stop_alloc
                is_destructive = True

            # stop the old destructive alloc first so its resources free up
            # (atomic place/stop pairing, ref reconcile_util.go:13-17)
            if is_destructive:
                self.plan.append_stopped_alloc(
                    prev, missing.stop_status_description)

            # check job still requires this tg
            if self.job.lookup_task_group(tg.name) is None:
                continue

            # canary gate: non-canary placements run at the downgraded
            # job version (old resources/constraints, ref :500)
            tg, place_job, place_dep_id = self.resolve_placement_job(
                missing, tg, deployment_id)
            if place_job is not None:
                self.stack.set_job(place_job)

            options = SelectOptions(alloc_name=name)
            if prev is not None:
                penalty = {prev.node_id}
                if prev.reschedule_tracker:
                    for ev in prev.reschedule_tracker.events:
                        penalty.add(ev.prev_node_id)
                options.penalty_node_ids = penalty
                # sticky ephemeral disk => prefer previous node
                if tg.ephemeral_disk.sticky and not (
                        isinstance(missing, AllocPlaceResult) and missing.lost):
                    node = self.state.node_by_id(prev.node_id)
                    if node is not None:
                        options.preferred_nodes = [node]

            option = self._select_next_option(tg, options)
            if place_job is not None:
                self.stack.set_job(self.job)        # restore after select
            # per-DC availability survives the per-select metric reset
            # (ref generic_sched.go computePlacements re-sets NodesAvailable)
            self.ctx.metrics.nodes_available = dict(self._nodes_by_dc)
            if option is not None:
                self._handle_preemptions(option)
                # per-alloc wrapper kept (the ranked task_resources vary
                # per option) — accepted PERF001 remnant, see
                # .nomadlint-baseline.json; the shared row is pooled
                resources = AllocatedResources(
                    tasks=dict(option.task_resources),
                    shared=option.alloc_resources or
                    skeleton_for(self._skel, tg, False).shared_total.shared)
                alloc = Allocation(
                    id=new_id(),
                    namespace=self.eval.namespace,
                    eval_id=self.eval.id,
                    name=name,
                    job_id=self.eval.job_id,
                    task_group=tg.name,
                    metrics=self.ctx.metrics.copy(),
                    node_id=option.node.id,
                    node_name=option.node.name,
                    deployment_id=place_dep_id,
                    allocated_resources=resources,
                    desired_status="run",
                    client_status="pending",
                )
                canary = isinstance(missing, AllocPlaceResult) and missing.canary
                if prev is not None:
                    alloc.previous_allocation = prev.id
                    if isinstance(missing, AllocPlaceResult) and missing.reschedule:
                        self._update_reschedule_tracker(alloc, prev)
                if place_dep_id and canary:
                    alloc.deployment_status = AllocDeploymentStatus(canary=True)
                    if self.plan.deployment is not None:
                        ds = self.plan.deployment.task_groups.get(tg.name)
                        if ds is not None:
                            ds.placed_canaries.append(alloc.id)
                self.plan.append_alloc(alloc, place_job)
            else:
                # failed placement: restore the stop we optimistically made
                if is_destructive:
                    self.plan.pop_update(prev)
                    self.queued_allocs[tg.name] = \
                        self.queued_allocs.get(tg.name, 0) - 1
                self.failed_tg_allocs[tg.name] = self.ctx.metrics.copy()
        return True

    def _downgraded_job_for_placement(self, tg_name: str,
                                      min_job_version: int):
        """-> (deployment_id, job) of the latest promoted/non-canaried
        job version — the version a non-canary placement must run at
        while canaries gate the new version (ref generic_sched.go:434
        downgradedJobForPlacement). Cached per (tg, min_version) for the
        eval: the result is snapshot-invariant, and a canary-gated job
        losing a node resolves it once per group, not once per alloc."""
        cache = getattr(self, "_downgrade_cache", None)
        if cache is None:
            cache = self._downgrade_cache = {}
        key = (tg_name, min_job_version)
        if key in cache:
            return cache[key]
        out = self._downgraded_job_uncached(tg_name, min_job_version)
        cache[key] = out
        return out

    def _downgraded_job_uncached(self, tg_name: str, min_job_version: int):
        ns, job_id = self.job.namespace, self.job.id
        deployments = list(self.state.deployments_by_job(ns, job_id))
        deployments.sort(key=lambda d: d.job_version, reverse=True)
        for d in deployments:
            ds = d.task_groups.get(tg_name)
            # zero desired_canaries: that version rolled without canaries
            if ds is not None and (ds.promoted or ds.desired_canaries == 0):
                return d.id, self.state.job_by_version(ns, job_id,
                                                       d.job_version)
        # latest stable version may predate any deployment (no update
        # stanza => no deployment record)
        job = self.state.job_by_version(ns, job_id, min_job_version)
        if job is not None and job.update is None:
            return "", job
        return "", None

    def resolve_placement_job(self, missing, tg, deployment_id: str):
        """-> (tg, job_override, deployment_id) honoring the reconciler's
        downgrade_non_canary flag: while a canary gate is up, non-canary
        placements (migrations, lost replacements, scale-ups) run at the
        old job version, with the old group's resources and constraints
        (ref generic_sched.go:500). job_override is None when the plan
        job applies."""
        from .reconcile import AllocPlaceResult
        if not (isinstance(missing, AllocPlaceResult) and
                missing.downgrade_non_canary):
            return tg, None, deployment_id
        did, djob = self._downgraded_job_for_placement(
            tg.name, missing.min_job_version)
        if djob is not None and djob.version >= missing.min_job_version:
            dtg = djob.lookup_task_group(tg.name)
            if dtg is not None:
                # `did` verbatim, INCLUDING empty (ref :500 assigns dID
                # as-is): attaching an old-version placement to the
                # current canary deployment would pollute its
                # placed/healthy accounting and progress deadline
                return dtg, djob, did
        if self.ctx.logger:
            self.ctx.logger(
                f"sched: no downgraded job version for {tg.name}; "
                f"placing at the latest")
        return tg, None, deployment_id

    def _select_next_option(self, tg, options: SelectOptions):
        """ref generic_sched.go:773 selectNextOption — retry with preemption
        when enabled."""
        option = self.stack.select(tg, options)
        if option is None:
            cfg = self.ctx.scheduler_config.preemption_config
            enabled = (cfg.batch_scheduler_enabled if self.batch
                       else cfg.service_scheduler_enabled)
            if enabled:
                options.preempt = True
                option = self.stack.select(tg, options)
        return option

    def _handle_preemptions(self, option) -> None:
        """ref generic_sched.go:795 handlePreemptions"""
        if not option.preempted_allocs:
            return
        # the preempting alloc id isn't known yet; use eval id marker
        for victim in option.preempted_allocs:
            self.plan.append_preempted_alloc(victim, self.eval.id)

    def _update_reschedule_tracker(self, alloc: Allocation,
                                   prev: Allocation) -> None:
        """ref generic_sched.go updateRescheduleTracker"""
        from ..structs import RescheduleEvent, RescheduleTracker
        events = []
        if prev.reschedule_tracker:
            events = list(prev.reschedule_tracker.events)
        events.append(RescheduleEvent(
            # event timestamps are observability data, not decisions
            # nomadlint: disable=DET001 — spec wall clock
            reschedule_time_unix=time.time(),
            prev_alloc_id=prev.id,
            prev_node_id=prev.node_id))
        # keep bounded history (ref structs.go maxPastRescheduleEvents = 5)
        alloc.reschedule_tracker = RescheduleTracker(events=events[-5:])

    # ------------------------------------------------------------- status

    def _set_status(self, status: str, desc: str) -> None:
        ev = self.eval.copy()
        ev.status = status
        ev.status_description = desc
        if self.blocked is not None:
            ev.blocked_eval = self.blocked.id
        ev.failed_tg_allocs = dict(self.failed_tg_allocs)
        ev.queued_allocations = dict(self.queued_allocs)
        self.planner.update_eval(ev)
