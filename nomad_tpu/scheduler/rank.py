"""Ranking iterators (ref scheduler/rank.go). BinPackIterator.Next
(rank.go:193-527) is THE hot loop — the scalar oracle that
nomad_tpu.solver reformulates as dense batched tensor ops.
"""
from __future__ import annotations

import math
from typing import Optional

from ..structs import (
    AllocatedResources, AllocatedSharedResources, AllocatedTaskResources,
    Allocation, NetworkIndex, Node, TaskGroup, allocs_fit, score_fit_binpack,
    score_fit_spread, BINPACK_MAX_FIT_SCORE, SCHED_ALG_SPREAD,
)
from .context import EvalContext
from .feasible import resolve_target, check_constraint


class RankedNode:
    """A node option flowing down the rank stack (ref rank.go:21)."""

    __slots__ = ("node", "final_score", "scores", "task_resources",
                 "alloc_resources", "preempted_allocs", "_proposed")

    def __init__(self, node: Node):
        self.node = node
        self.final_score = 0.0
        self.scores: list[float] = []
        self.task_resources: dict[str, AllocatedTaskResources] = {}
        self.alloc_resources: Optional[AllocatedSharedResources] = None
        self.preempted_allocs: Optional[list[Allocation]] = None
        self._proposed: Optional[list[Allocation]] = None

    def proposed_allocs(self, ctx: EvalContext) -> list[Allocation]:
        if self._proposed is None:
            self._proposed = ctx.proposed_allocs(self.node.id)
        return self._proposed

    def set_task_resources(self, task, resources) -> None:
        self.task_resources[task.name] = resources


class RankIterator:
    def next(self) -> Optional[RankedNode]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class FeasibleRankIterator(RankIterator):
    """Adapts a FeasibleIterator into the rank chain (ref rank.go:100)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        node = self.source.next()
        if node is None:
            return None
        return RankedNode(node)

    def reset(self) -> None:
        self.source.reset()


class BinPackIterator(RankIterator):
    """Scores nodes by fit; assigns ports/devices/cores as it goes
    (ref rank.go:151, Next:193-527)."""

    def __init__(self, ctx: EvalContext, source: RankIterator,
                 evict: bool = False, priority: int = 0,
                 algorithm: str = "binpack"):
        self.ctx = ctx
        self.source = source
        self.evict = evict
        self.priority = priority
        self.job_id = ""
        self.task_group: Optional[TaskGroup] = None
        self.score_fit = (score_fit_spread if algorithm == SCHED_ALG_SPREAD
                          else score_fit_binpack)
        self.memory_oversubscription = \
            ctx.scheduler_config.memory_oversubscription_enabled

    def set_job(self, job) -> None:
        self.job_id = job.id
        if job.priority:
            self.priority = job.priority

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None:
                return None
            result = self._try_node(option)
            if result is not None:
                return result

    def _try_node(self, option: RankedNode) -> Optional[RankedNode]:
        from .preemption import Preemptor
        ctx, tg = self.ctx, self.task_group
        node = option.node
        proposed = list(option.proposed_allocs(ctx))

        net_idx = NetworkIndex()
        net_idx.set_node(node)
        net_idx.add_allocs(proposed)

        total = AllocatedResources(
            shared=AllocatedSharedResources(disk_mb=tg.ephemeral_disk.size_mb))
        allocs_to_preempt: list[Allocation] = []

        preemptor = None
        if self.evict:
            preemptor = Preemptor(self.priority, ctx, self.job_id)
            preemptor.set_node(node)
            current_preemptions = []
            if ctx.plan is not None:
                for allocs in ctx.plan.node_preemptions.values():
                    current_preemptions.extend(allocs)
            preemptor.set_preemptions(current_preemptions)

        # group-level network (ref rank.go:248-324)
        if tg.networks:
            ask = tg.networks[0]
            offer, err = net_idx.assign_network(ask)
            if offer is None and self.evict and preemptor is not None:
                preemptor.set_candidates(proposed)
                victims = preemptor.preempt_for_network(ask, net_idx)
                if victims:
                    allocs_to_preempt.extend(victims)
                    victim_ids = {v.id for v in victims}
                    proposed = [a for a in proposed if a.id not in victim_ids]
                    net_idx = NetworkIndex()
                    net_idx.set_node(node)
                    net_idx.add_allocs(proposed)
                    offer, err = net_idx.assign_network(ask)
            if offer is None:
                ctx.metrics.exhausted_node(node, f"network: {err}")
                return None
            net_idx.add_reserved(offer)
            total.shared.networks = [offer]
            total.shared.ports = [
                {"label": p.label, "value": p.value, "to": p.to,
                 "host_ip": offer.ip}
                for p in offer.reserved_ports + offer.dynamic_ports]
            option.alloc_resources = AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb,
                networks=[offer], ports=total.shared.ports)

        # one device allocator per node attempt — offers reserved as assigned
        # so multiple device asks never double-book an instance
        from .device import DeviceAllocator
        dev_alloc = DeviceAllocator(ctx, node)
        dev_alloc.add_allocs(proposed)

        # per-task resources (ref rank.go:325-470)
        for task in tg.tasks:
            tr = AllocatedTaskResources(
                cpu_shares=task.resources.cpu,
                memory_mb=task.resources.memory_mb)
            if self.memory_oversubscription:
                tr.memory_max_mb = task.resources.memory_max_mb

            if task.resources.networks:
                ask = task.resources.networks[0]
                offer, err = net_idx.assign_network(ask)
                if offer is None and self.evict and preemptor is not None:
                    preemptor.set_candidates(proposed)
                    victims = preemptor.preempt_for_network(ask, net_idx)
                    if victims:
                        allocs_to_preempt.extend(victims)
                        victim_ids = {v.id for v in victims}
                        proposed = [a for a in proposed if a.id not in victim_ids]
                        net_idx = NetworkIndex()
                        net_idx.set_node(node)
                        net_idx.add_allocs(proposed)
                        offer, err = net_idx.assign_network(ask)
                if offer is None:
                    ctx.metrics.exhausted_node(node, f"network: {err}")
                    return None
                net_idx.add_reserved(offer)
                tr.networks = [offer]

            # devices (ref rank.go:389-436)
            for req in task.resources.devices:
                offer_dev, affinity_score, err = dev_alloc.assign_device(req)
                if offer_dev is None:
                    ctx.metrics.exhausted_node(node, f"devices: {err}")
                    return None
                dev_alloc.add_reserved(offer_dev)
                tr.devices.append(offer_dev)
                if req.affinities:
                    option.scores.append(affinity_score)

            # reserved cores (ref rank.go:438-466)
            if task.resources.cores > 0:
                node_cores = set(node.node_resources.cpu.reservable_cores)
                taken: set[int] = set()
                for alloc in proposed:
                    taken |= set(alloc.comparable_resources().reserved_cores)
                for assigned in total.tasks.values():
                    taken |= set(assigned.reserved_cores)
                avail = sorted(node_cores - taken)
                if len(avail) < task.resources.cores:
                    ctx.metrics.exhausted_node(node, "cores")
                    return None
                tr.reserved_cores = tuple(avail[:task.resources.cores])
                total_cores = node.node_resources.cpu.total_core_count or 1
                shares_per_core = node.node_resources.cpu.cpu_shares // total_cores
                tr.cpu_shares = shares_per_core * task.resources.cores

            option.set_task_resources(task, tr)
            total.tasks[task.name] = tr

        # final fit check (ref rank.go:470-510)
        current = proposed
        candidate = Allocation(allocated_resources=total)
        fit, dim, util = allocs_fit(node, proposed + [candidate], net_idx)
        if not fit:
            if not self.evict or preemptor is None:
                ctx.metrics.exhausted_node(node, dim)
                return None
            preemptor.set_candidates(current)
            victims = preemptor.preempt_for_task_group(total)
            if not victims:
                ctx.metrics.exhausted_node(node, dim)
                return None
            allocs_to_preempt.extend(victims)
            victim_ids = {v.id for v in victims}
            remaining = [a for a in proposed if a.id not in victim_ids]
            fit, dim, util = allocs_fit(node, remaining + [candidate])
            if not fit:
                ctx.metrics.exhausted_node(node, dim)
                return None

        if allocs_to_preempt:
            option.preempted_allocs = allocs_to_preempt

        fitness = self.score_fit(node, util)
        normalized = fitness / BINPACK_MAX_FIT_SCORE
        option.scores.append(normalized)
        ctx.metrics.score_node(node.id, "binpack", normalized)
        return option


class JobAntiAffinityIterator(RankIterator):
    """Penalize co-placement with same job+TG allocs (ref rank.go:536)."""

    def __init__(self, ctx: EvalContext, source: RankIterator, job_id: str = ""):
        self.ctx = ctx
        self.source = source
        self.job_id = job_id
        self.task_group = ""
        self.desired_count = 0

    def set_job(self, job) -> None:
        self.job_id = job.id

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg.name
        self.desired_count = tg.count

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        proposed = option.proposed_allocs(self.ctx)
        collisions = sum(1 for a in proposed
                         if a.job_id == self.job_id
                         and a.task_group == self.task_group)
        if collisions > 0 and self.desired_count > 0:
            penalty = -1.0 * (collisions + 1) / self.desired_count
            option.scores.append(penalty)
            self.ctx.metrics.score_node(option.node.id, "job-anti-affinity",
                                        penalty)
        return option

    def reset(self) -> None:
        self.source.reset()


class NodeReschedulingPenaltyIterator(RankIterator):
    """-1 score on nodes where this alloc previously failed (ref rank.go:606)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.penalty_nodes: set[str] = set()

    def set_penalty_nodes(self, nodes: set[str]) -> None:
        self.penalty_nodes = nodes or set()

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if option.node.id in self.penalty_nodes:
            option.scores.append(-1.0)
            self.ctx.metrics.score_node(option.node.id,
                                        "node-reschedule-penalty", -1.0)
        return option

    def reset(self) -> None:
        self.penalty_nodes = set()
        self.source.reset()


class NodeAffinityIterator(RankIterator):
    """Weighted affinity scoring (ref rank.go:650)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.job_affinities = []
        self.affinities = []

    def set_job(self, job) -> None:
        self.job_affinities = list(job.affinities)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.affinities = self.job_affinities + list(tg.affinities)
        for task in tg.tasks:
            self.affinities.extend(task.affinities)

    def has_affinities(self) -> bool:
        return bool(self.affinities)

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not self.affinities:
            return option
        sum_weight = sum(abs(a.weight) for a in self.affinities)
        total = 0.0
        for aff in self.affinities:
            if self._matches(aff, option.node):
                total += float(aff.weight)
        norm = total / sum_weight if sum_weight else 0.0
        if norm != 0.0:
            # normalized to [-1, 1] like the reference (weights are percents)
            score = norm / 100.0 if abs(norm) > 1 else norm
            option.scores.append(score)
            self.ctx.metrics.score_node(option.node.id, "node-affinity", score)
        return option

    def _matches(self, aff, node: Node) -> bool:
        lval, lok = resolve_target(aff.ltarget, node)
        rval, rok = resolve_target(aff.rtarget, node)
        return check_constraint(self.ctx, aff.operand, lval, rval, lok, rok)

    def reset(self) -> None:
        self.source.reset()


class ScoreNormalizationIterator(RankIterator):
    """final_score = mean(scores) (ref rank.go:737)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if option.scores:
            option.final_score = sum(option.scores) / len(option.scores)
        self.ctx.metrics.score_node(option.node.id, "normalized-score",
                                    option.final_score)
        return option

    def reset(self) -> None:
        self.source.reset()


class PreemptionScoringIterator(RankIterator):
    """Logistic preemption score in (0,1) (ref rank.go:775)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not option.preempted_allocs:
            return option
        score = preemption_score(net_priority(option.preempted_allocs))
        option.scores.append(score)
        self.ctx.metrics.score_node(option.node.id, "preemption", score)
        return option

    def reset(self) -> None:
        self.source.reset()


def net_priority(allocs: list[Allocation]) -> float:
    """max priority + sum/max penalty (ref rank.go:811)."""
    max_p = 0.0
    total = 0
    for a in allocs:
        p = a.job.priority if a.job else 50
        max_p = max(max_p, float(p))
        total += p
    if max_p == 0:
        return 0.0
    return max_p + (total / max_p)


def preemption_score(netp: float) -> float:
    """Logistic curve, inflection ~2048 (ref rank.go:834)."""
    rate, origin = 0.0048, 2048.0
    return 1.0 / (1.0 + math.exp(rate * (netp - origin)))
