"""Device allocator: selects device instances for a task's device asks with
affinity scoring (ref scheduler/device.go).
"""
from __future__ import annotations

from typing import Optional

from ..structs import AllocatedDeviceResource, Node, RequestedDevice
from .feasible import check_constraint, _resolve_device_target


class DeviceAllocator:
    def __init__(self, ctx, node: Node):
        self.ctx = ctx
        self.node = node
        # (vendor,type,name) -> {instance_id: use_count}
        self.instances: dict[tuple, dict[str, int]] = {}
        self.devices: dict[tuple, object] = {}
        for dev in node.node_resources.devices:
            key = dev.id_tuple()
            self.devices[key] = dev
            self.instances[key] = {inst.id: 0 for inst in dev.instances
                                   if inst.healthy}

    def add_allocs(self, allocs) -> None:
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for ad in tr.devices:
                    key = (ad.vendor, ad.type, ad.name)
                    insts = self.instances.get(key)
                    if insts is None:
                        continue
                    for dev_id in ad.device_ids:
                        if dev_id in insts:
                            insts[dev_id] += 1

    def add_reserved(self, offer: AllocatedDeviceResource) -> None:
        key = (offer.vendor, offer.type, offer.name)
        insts = self.instances.get(key, {})
        for dev_id in offer.device_ids:
            if dev_id in insts:
                insts[dev_id] += 1

    def assign_device(self, ask: RequestedDevice
                      ) -> tuple[Optional[AllocatedDeviceResource], float, str]:
        """Pick the best matching device group with enough free instances.
        Returns (offer, normalized affinity score, error reason)."""
        best = None
        best_score = 0.0
        err = "no devices match request"
        for key, dev in self.devices.items():
            if not dev.matches(ask):
                continue
            if not self._meets_constraints(dev, ask):
                err = "device constraints not met"
                continue
            free = [i for i, c in self.instances.get(key, {}).items() if c == 0]
            if len(free) < ask.count:
                err = "no device instances available"
                continue
            score = self._affinity_score(dev, ask)
            if best is None or score > best_score:
                best = (key, dev, free)
                best_score = score
        if best is None:
            return None, 0.0, err
        key, dev, free = best
        offer = AllocatedDeviceResource(
            vendor=key[0], type=key[1], name=key[2],
            device_ids=free[:ask.count])
        return offer, best_score, ""

    def _meets_constraints(self, dev, ask: RequestedDevice) -> bool:
        for c in ask.constraints:
            lval, lok = _resolve_device_target(c.ltarget, dev)
            rval, rok = _resolve_device_target(c.rtarget, dev)
            if not check_constraint(self.ctx, c.operand, lval, rval, lok, rok):
                return False
        return True

    def _affinity_score(self, dev, ask: RequestedDevice) -> float:
        if not ask.affinities:
            return 0.0
        total, sum_weight = 0.0, 0.0
        for aff in ask.affinities:
            sum_weight += abs(aff.weight)
            lval, lok = _resolve_device_target(aff.ltarget, dev)
            rval, rok = _resolve_device_target(aff.rtarget, dev)
            if check_constraint(self.ctx, aff.operand, lval, rval, lok, rok):
                total += float(aff.weight)
        return total / sum_weight if sum_weight else 0.0
