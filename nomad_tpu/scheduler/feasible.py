"""Feasibility checking (ref scheduler/feasible.go).

Source iterators + a chain of per-node checkers. The FeasibilityWrapper caches
verdicts per computed node class (ref context.go:190) — the same escape-hatch
the TPU solver keeps for irregular constraints (SURVEY.md hard part 2).
"""
from __future__ import annotations

import random
from typing import Iterable, Optional

from ..structs import (
    Node, TaskGroup, Job, Constraint,
    OP_DISTINCT_HOSTS, OP_DISTINCT_PROPERTY, OP_EQ, OP_GT, OP_GTE, OP_IS_NOT_SET,
    OP_IS_SET, OP_LT, OP_LTE, OP_NEQ, OP_REGEX, OP_SEMVER, OP_SET_CONTAINS,
    OP_SET_CONTAINS_ALL, OP_SET_CONTAINS_ANY, OP_VERSION,
)
from .context import (
    EvalContext, EVAL_COMPUTED_CLASS_ELIGIBLE, EVAL_COMPUTED_CLASS_ESCAPED,
    EVAL_COMPUTED_CLASS_IGNORE, EVAL_COMPUTED_CLASS_INELIGIBLE,
    EVAL_COMPUTED_CLASS_UNKNOWN,
)

# ---------------------------------------------------------------- versions


class Version:
    """Minimal go-version-compatible version: dotted numeric segments with an
    optional -prerelease suffix (release > prerelease)."""

    __slots__ = ("segments", "prerelease")

    def __init__(self, s: str):
        s = s.strip().lstrip("v")
        if "+" in s:               # build metadata ignored
            s = s.split("+", 1)[0]
        if "-" in s:
            core, self.prerelease = s.split("-", 1)
        else:
            core, self.prerelease = s, ""
        segs = []
        for part in core.split("."):
            segs.append(int(part))
        if not segs:
            raise ValueError(f"bad version {s!r}")
        while len(segs) < 3:
            segs.append(0)
        self.segments = tuple(segs)

    def _key(self):
        # A prerelease sorts before its release
        return (self.segments, 0 if self.prerelease == "" else -1,
                self.prerelease)

    def __lt__(self, o): return self._key() < o._key()
    def __le__(self, o): return self._key() <= o._key()
    def __gt__(self, o): return self._key() > o._key()
    def __ge__(self, o): return self._key() >= o._key()
    def __eq__(self, o): return self._key() == o._key()


def parse_version_constraint(spec: str) -> Optional[list[tuple[str, Version]]]:
    """Parse "> 1.2, <= 2.0" / "~> 1.2" into [(op, version)] or None."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        op = "="
        for candidate in ("~>", ">=", "<=", "!=", ">", "<", "="):
            if part.startswith(candidate):
                op = candidate
                part = part[len(candidate):].strip()
                break
        try:
            out.append((op, Version(part)))
        except (ValueError, TypeError):
            return None
    return out or None


def check_version_constraint(version: Version,
                             constraints: list[tuple[str, Version]]) -> bool:
    for op, cv in constraints:
        if op == "=" and not version == cv:
            return False
        if op == "!=" and not version != cv:
            return False
        if op == ">" and not version > cv:
            return False
        if op == ">=" and not version >= cv:
            return False
        if op == "<" and not version < cv:
            return False
        if op == "<=" and not version <= cv:
            return False
        if op == "~>":
            # pessimistic: >= cv and < next significant segment
            if not version >= cv:
                return False
            segs = list(cv.segments)
            # bump the second-to-last specified segment
            upper = segs[:-1]
            if len(upper) == 0:
                upper = [segs[0] + 1]
            else:
                upper[-1] += 1
            upper_v = Version(".".join(str(x) for x in upper))
            if not version < upper_v:
                return False
    return True


# ------------------------------------------------------------- resolution

def resolve_target(target: str, node: Node) -> tuple[Optional[str], bool]:
    """Resolve a constraint target against a node (ref feasible.go:748)."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        key = target[len("${attr."):-1]
        val = node.attributes.get(key)
        return val, val is not None
    if target.startswith("${meta."):
        key = target[len("${meta."):-1]
        val = node.meta.get(key)
        return val, val is not None
    return None, False


def check_constraint(ctx: EvalContext, operand: str, lval, rval,
                     lfound: bool, rfound: bool) -> bool:
    """ref feasible.go:785 checkConstraint"""
    if operand in (OP_DISTINCT_HOSTS, OP_DISTINCT_PROPERTY):
        return True  # handled by dedicated iterators
    if operand in (OP_EQ, "==", "is"):
        return lfound and rfound and lval == rval
    if operand in (OP_NEQ, "not"):
        return lval != rval
    if operand in (OP_LT, OP_LTE, OP_GT, OP_GTE):
        if not (lfound and rfound and isinstance(lval, str)
                and isinstance(rval, str)):
            return False
        return {OP_LT: lval < rval, OP_LTE: lval <= rval,
                OP_GT: lval > rval, OP_GTE: lval >= rval}[operand]
    if operand == OP_IS_SET:
        return lfound
    if operand == OP_IS_NOT_SET:
        return not lfound
    if operand in (OP_VERSION, OP_SEMVER):
        if not (lfound and rfound):
            return False
        try:
            v = Version(str(lval))
        except (ValueError, TypeError):
            return False
        cache = (ctx.cache.version_constraint if operand == OP_VERSION
                 else ctx.cache.semver_constraint)
        cons = cache.get(rval)
        if cons is None:
            cons = parse_version_constraint(str(rval))
            if cons is None:
                return False
            cache[rval] = cons
        return check_version_constraint(v, cons)
    if operand == OP_REGEX:
        if not (lfound and rfound and isinstance(lval, str)):
            return False
        r = ctx.regexp(str(rval))
        return r is not None and r.search(lval) is not None
    if operand in (OP_SET_CONTAINS, OP_SET_CONTAINS_ALL):
        if not (lfound and rfound):
            return False
        have = {p.strip() for p in str(lval).split(",")}
        return all(w.strip() in have for w in str(rval).split(","))
    if operand == OP_SET_CONTAINS_ANY:
        if not (lfound and rfound):
            return False
        have = {p.strip() for p in str(lval).split(",")}
        return any(w.strip() in have for w in str(rval).split(","))
    return False


# -------------------------------------------------------------- iterators


class FeasibleIterator:
    """Pull-iterator over feasible nodes; mirrors the reference's lazy
    iterator chain so limit/select semantics match."""

    def next(self) -> Optional[Node]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class StaticIterator(FeasibleIterator):
    """Fixed node order (ref feasible.go:74)."""

    def __init__(self, ctx: EvalContext, nodes: list[Node]):
        self.ctx = ctx
        self.nodes = list(nodes)
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        if self.offset == len(self.nodes):
            return None
        node = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.nodes_evaluated += 1   # ref feasible.go:86
        return node

    def reset(self) -> None:
        self.offset = 0
        self.seen = 0

    def set_nodes(self, nodes: list[Node]) -> None:
        self.nodes = list(nodes)
        self.reset()


def new_random_iterator(ctx: EvalContext, nodes: list[Node],
                        rng: Optional[random.Random] = None) -> StaticIterator:
    """Shuffled static iterator (ref feasible.go:122 NewRandomIterator)."""
    nodes = list(nodes)
    (rng or random).shuffle(nodes)
    return StaticIterator(ctx, nodes)


class ChecksFeasibility:
    def feasible(self, node: Node) -> bool:
        raise NotImplementedError


class DriverChecker(ChecksFeasibility):
    """Node runs healthy drivers for all tasks (ref feasible.go:433)."""

    def __init__(self, ctx: EvalContext, drivers: Optional[set[str]] = None):
        self.ctx = ctx
        self.drivers = drivers or set()

    def set_drivers(self, drivers: set[str]) -> None:
        self.drivers = drivers

    def feasible(self, node: Node) -> bool:
        for driver in self.drivers:
            info = node.drivers.get(driver)
            if info is not None:
                if not (info.detected and info.healthy):
                    self.ctx.metrics.filter_node(node, f"missing drivers")
                    return False
                continue
            # legacy attribute form: driver.<name> = "1"
            raw = node.attributes.get(f"driver.{driver}")
            if raw not in ("1", "true", "True"):
                self.ctx.metrics.filter_node(node, "missing drivers")
                return False
        return True


class ConstraintChecker(ChecksFeasibility):
    """ref feasible.go:709"""

    def __init__(self, ctx: EvalContext, constraints: list[Constraint]):
        self.ctx = ctx
        self.constraints = constraints

    def set_constraints(self, constraints: list[Constraint]) -> None:
        self.constraints = constraints

    def feasible(self, node: Node) -> bool:
        for c in self.constraints:
            if not self._meets(c, node):
                self.ctx.metrics.filter_node(node, str(c))
                return False
        return True

    def _meets(self, c: Constraint, node: Node) -> bool:
        lval, lok = resolve_target(c.ltarget, node)
        rval, rok = resolve_target(c.rtarget, node)
        return check_constraint(self.ctx, c.operand, lval, rval, lok, rok)


class HostVolumeChecker(ChecksFeasibility):
    """Node exposes all requested host volumes (ref feasible.go:132)."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.volumes: list = []

    def set_volumes(self, alloc_name: str, volumes: dict) -> None:
        self.volumes = []
        for req in volumes.values():
            if req.type != "host":
                continue
            source = req.source
            if req.per_alloc:
                from ..structs import alloc_name_index
                source = f"{source}[{alloc_name_index(alloc_name)}]"
            self.volumes.append((source, req.read_only))

    def feasible(self, node: Node) -> bool:
        for source, read_only in self.volumes:
            vol = node.host_volumes.get(source)
            if vol is None:
                self.ctx.metrics.filter_node(node, "missing compatible host volumes")
                return False
            if vol.read_only and not read_only:
                self.ctx.metrics.filter_node(node, "missing compatible host volumes")
                return False
        return True


class NetworkChecker(ChecksFeasibility):
    """Coarse network feasibility: host networks exist for requested port
    host_networks and required mode supported (ref feasible.go:341)."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.network = None

    def set_network(self, network) -> None:
        self.network = network

    def feasible(self, node: Node) -> bool:
        if self.network is None:
            return True
        if self.network.mode in ("bridge", "cni") or \
           self.network.mode.startswith("cni/"):
            ok = node.attributes.get("plugins.cni.version.bridge") or \
                node.attributes.get("network.bridge", "1")
            if not ok:
                self.ctx.metrics.filter_node(node, "missing network")
                return False
        # host networks for ports
        want = set()
        for p in list(self.network.reserved_ports) + list(self.network.dynamic_ports):
            if p.host_network and p.host_network != "default":
                want.add(p.host_network)
        if want:
            have = {nn.mode for nn in node.node_resources.node_networks}
            names = set()
            for nn in node.node_resources.node_networks:
                for addr in nn.addresses:
                    names.add(addr.get("alias", ""))
            if not want <= names:
                self.ctx.metrics.filter_node(node, "missing host network")
                return False
        return True


class DeviceChecker(ChecksFeasibility):
    """Node has device instances matching every device ask, including
    count and device constraints (ref scheduler/device.go + feasible device
    checker)."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.required: list = []

    def set_task_group(self, tg: TaskGroup) -> None:
        self.required = []
        for task in tg.tasks:
            for dev in task.resources.devices:
                self.required.append(dev)

    def feasible(self, node: Node) -> bool:
        if not self.required:
            return True
        for ask in self.required:
            if not self._has(node, ask):
                self.ctx.metrics.filter_node(node, "missing devices")
                return False
        return True

    def _has(self, node: Node, ask) -> bool:
        total = 0
        for dev in node.node_resources.devices:
            if not dev.matches(ask):
                continue
            if not self._device_meets_constraints(dev, ask):
                continue
            total += sum(1 for inst in dev.instances if inst.healthy)
        return total >= ask.count

    def _device_meets_constraints(self, dev, ask) -> bool:
        for c in ask.constraints:
            lval, lok = _resolve_device_target(c.ltarget, dev)
            rval, rok = _resolve_device_target(c.rtarget, dev)
            if not check_constraint(self.ctx, c.operand, lval, rval, lok, rok):
                return False
        return True


def _resolve_device_target(target: str, dev) -> tuple[Optional[str], bool]:
    if not target.startswith("${"):
        return target, True
    if target.startswith("${device.attr."):
        key = target[len("${device.attr."):-1]
        val = dev.attributes.get(key)
        return (str(val), True) if val is not None else (None, False)
    if target == "${device.model}":
        return dev.name, True
    if target == "${device.vendor}":
        return dev.vendor, True
    if target == "${device.type}":
        return dev.type, True
    return None, False


class CSIVolumeChecker(ChecksFeasibility):
    """Node runs healthy CSI node plugins for requested CSI volumes, and the
    volume itself is schedulable with free claims for the requested mode
    (ref feasible.go:209 CSIVolumeChecker, csi.go WriteFreeClaims)."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.plugins: set[str] = set()
        # (volume-or-None, source, read_only) per requested CSI volume —
        # claim capacity is volume-wide, checked once per feasibility pass
        self.volumes: list[tuple] = []
        self.namespace = "default"
        self.job_id = ""

    def set_volumes(self, volumes: dict, namespace: str = "default",
                    csi_volume_lookup=None, job_id: str = "") -> None:
        self.plugins = set()
        self.volumes = []
        self.namespace = namespace
        self.job_id = job_id
        if csi_volume_lookup is None:
            by_id = getattr(self.ctx.state, "csi_volume_by_id", None)
            if by_id is not None:
                csi_volume_lookup = lambda src: by_id(namespace, src)  # noqa: E731
        for req in volumes.values():
            if req.type == "csi":
                vol = None
                plugin = None
                if csi_volume_lookup is not None:
                    vol = csi_volume_lookup(req.source)
                    plugin = getattr(vol, "plugin_id", None) if vol else None
                self.plugins.add(plugin or req.source)
                self.volumes.append(
                    (vol, req.source, getattr(req, "read_only", False)))

    def feasible(self, node: Node) -> bool:
        if not self.plugins:
            return True
        for vol, source, read_only in self.volumes:
            if vol is not None:
                if not getattr(vol, "schedulable", True):
                    self.ctx.metrics.filter_node(
                        node, f"CSI volume {source} unschedulable")
                    return False
                mode = "read" if read_only else "write"
                if not vol.claim_ok(mode) and \
                        not self._claims_held_by_this_job(vol):
                    self.ctx.metrics.filter_node(
                        node, f"CSI volume {source} has no free claims")
                    return False
        for plugin in self.plugins:
            info = node.csi_node_plugins.get(plugin)
            if info is None or not info.get("healthy", False):
                self.ctx.metrics.filter_node(node, "missing CSI plugins")
                return False
        return True

    def _claims_held_by_this_job(self, vol) -> bool:
        """Claims held by allocs of the job being scheduled don't block it:
        a rolling update / reschedule of the claim-holding job must be able
        to place its replacement (ref feasible.go: blocking write claims
        only filter when they belong to a different job)."""
        if not self.job_id:
            return False
        alloc_by_id = getattr(self.ctx.state, "alloc_by_id", None)
        if alloc_by_id is None:
            return False
        for claim in vol.write_claims.values():
            alloc = alloc_by_id(claim.alloc_id)
            if alloc is None or alloc.namespace != self.namespace or \
                    alloc.job_id != self.job_id:
                return False
        return True


class FeasibilityWrapper(FeasibleIterator):
    """Wraps a source iterator with job-level and task-group-level checks,
    caching verdicts per computed node class (ref feasible.go
    FeasibilityWrapper + context.go EvalEligibility)."""

    def __init__(self, ctx: EvalContext, source: FeasibleIterator,
                 job_checks: list[ChecksFeasibility],
                 tg_checks: list[ChecksFeasibility],
                 tg_available: Optional[list[ChecksFeasibility]] = None):
        self.ctx = ctx
        self.source = source
        self.job_checks = job_checks
        self.tg_checks = tg_checks
        # "available" checks depend on state outside the computed node class
        # (CSI plugin health) so they can never be class-cached
        # (ref feasible.go FeasibilityWrapper tgAvailable)
        self.tg_available = tg_available or []
        self.tg_name = ""

    def set_task_group(self, tg: str) -> None:
        self.tg_name = tg

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[Node]:
        elig = self.ctx.eligibility
        while True:
            node = self.source.next()
            if node is None:
                return None
            klass = node.computed_class

            # job-level
            job_status = elig.job_status(klass)
            if job_status == EVAL_COMPUTED_CLASS_INELIGIBLE:
                # fast path still counts (ref feasible.go FeasibilityWrapper:
                # FilterNode "computed class ineligible")
                self.ctx.metrics.filter_node(node, "computed class ineligible")
                continue
            if job_status in (EVAL_COMPUTED_CLASS_UNKNOWN,
                              EVAL_COMPUTED_CLASS_ESCAPED,
                              EVAL_COMPUTED_CLASS_IGNORE):
                ok = all(c.feasible(node) for c in self.job_checks)
                if job_status == EVAL_COMPUTED_CLASS_UNKNOWN:
                    elig.set_job_eligibility(ok, klass)
                if not ok:
                    continue

            # task-group-level
            if self.tg_name:
                tg_status = elig.task_group_status(self.tg_name, klass)
                if tg_status == EVAL_COMPUTED_CLASS_INELIGIBLE:
                    self.ctx.metrics.filter_node(node,
                                                 "computed class ineligible")
                    continue
                if tg_status in (EVAL_COMPUTED_CLASS_UNKNOWN,
                                 EVAL_COMPUTED_CLASS_ESCAPED,
                                 EVAL_COMPUTED_CLASS_IGNORE):
                    ok = all(c.feasible(node) for c in self.tg_checks)
                    if tg_status == EVAL_COMPUTED_CLASS_UNKNOWN:
                        elig.set_task_group_eligibility(ok, self.tg_name, klass)
                    if not ok:
                        continue
                if not all(c.feasible(node) for c in self.tg_available):
                    continue
            return node


class DistinctHostsIterator(FeasibleIterator):
    """distinct_hosts: no two allocs of the same job/tg on one node
    (ref feasible.go:505)."""

    def __init__(self, ctx: EvalContext, source: FeasibleIterator):
        self.ctx = ctx
        self.source = source
        self.tg = None
        self.job = None

    def set_task_group(self, tg): self.tg = tg
    def set_job(self, job): self.job = job

    def reset(self) -> None:
        self.source.reset()

    def _enabled(self) -> bool:
        if self.job and any(c.operand == OP_DISTINCT_HOSTS
                            for c in self.job.constraints):
            return True
        return bool(self.tg and any(c.operand == OP_DISTINCT_HOSTS
                                    for c in self.tg.constraints))

    def next(self) -> Optional[Node]:
        enabled = self._enabled()
        while True:
            node = self.source.next()
            if node is None or not enabled:
                return node
            if self._satisfies(node):
                return node
            self.ctx.metrics.filter_node(node, OP_DISTINCT_HOSTS)

    def _satisfies(self, node: Node) -> bool:
        proposed = self.ctx.proposed_allocs(node.id)
        job_level = any(c.operand == OP_DISTINCT_HOSTS
                        for c in self.job.constraints) if self.job else False
        for alloc in proposed:
            if job_level:
                if self.job and alloc.job_id == self.job.id and \
                   alloc.namespace == self.job.namespace:
                    return False
            elif self.tg and alloc.task_group == self.tg.name and \
                    self.job and alloc.job_id == self.job.id:
                return False
        return True


class DistinctPropertyIterator(FeasibleIterator):
    """distinct_property: bound number of allocs per property value
    (ref feasible.go:604), backed by PropertySet."""

    def __init__(self, ctx: EvalContext, source: FeasibleIterator):
        self.ctx = ctx
        self.source = source
        self.job = None
        self.tg = None
        self.job_property_sets: list = []
        self.tg_property_sets: list = []

    def set_job(self, job) -> None:
        from .propertyset import PropertySet
        self.job = job
        self.job_property_sets = []
        for c in job.constraints:
            if c.operand == OP_DISTINCT_PROPERTY:
                ps = PropertySet(self.ctx, job)
                ps.set_job_constraint(c)
                self.job_property_sets.append(ps)

    def set_task_group(self, tg) -> None:
        from .propertyset import PropertySet
        self.tg = tg
        self.tg_property_sets = []
        for c in tg.constraints:
            if c.operand == OP_DISTINCT_PROPERTY:
                ps = PropertySet(self.ctx, self.job)
                ps.set_tg_constraint(c, tg.name)
                self.tg_property_sets.append(ps)

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[Node]:
        while True:
            node = self.source.next()
            if node is None:
                return None
            ok = True
            for ps in self.job_property_sets + self.tg_property_sets:
                satisfied, reason = ps.satisfies_distinct_properties(node)
                if not satisfied:
                    self.ctx.metrics.filter_node(node, reason)
                    ok = False
                    break
            if ok:
                return node
