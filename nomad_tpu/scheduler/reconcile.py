"""The allocation reconciler (ref scheduler/reconcile.go): diffs desired
(job) against actual (allocs) into place/stop/migrate/in-place/destructive/
canary sets, driving deployments and reschedules. Pure set algebra — no
placement decisions here; that's the stack/solver's job.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from ..structs import (
    Allocation, Deployment, DeploymentState, DeploymentStatusUpdate,
    DesiredUpdates, Evaluation, Job, Node, TaskGroup, new_deployment,
    ALLOC_CLIENT_LOST, ALLOC_CLIENT_RUNNING, ALLOC_CLIENT_UNKNOWN,
    DESC_CANARY, DESC_MIGRATING, DESC_NOT_NEEDED,
    DESC_RESCHEDULED, DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_PENDING, DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL, DEPLOYMENT_STATUS_CANCELLED,
    EVAL_STATUS_PENDING, TRIGGER_FAILED_FOLLOW_UP, TRIGGER_MAX_DISCONNECT,
    JOB_TYPE_BATCH,
)
from .reconcile_util import (
    AllocNameIndex, AllocSet, DelayedRescheduleInfo, alloc_matrix, difference,
    delay_by_stop_after_client_disconnect, filter_by_deployment,
    filter_by_rescheduleable, filter_by_tainted, filter_by_terminal, from_keys,
    name_order, name_set, split_disconnecting, split_reconnecting, union,
)

DESC_DEPLOYMENT_CANCELLED = "cancelled because job is stopped or newer version"
DESC_UNKNOWN = "alloc is unknown since its node is disconnected"
DESC_RECONNECTED = "replacement stopped: original alloc reconnected"
DESC_RECONNECT_EXPIRED = "alloc reconnected after max_client_disconnect"
DESC_RECONNECT_OK = "alloc reconnected within max_client_disconnect"
DESC_RECONNECT_OUTDATED = "reconnected alloc is an outdated job version"
DESC_DUP_NAME = "duplicate name slot holder"


def _rank_name_slot_holders(group: list) -> list:
    """Order duplicate holders of one name slot best-first: live before
    terminal, then highest job version, then the earliest-created (the
    true original). Shared by the reconnect same-pass dedup and the
    computeStop convergent cleanup so the keeper policy can't diverge."""
    return sorted(group, key=lambda p: (
        p[1].terminal_status(),
        -(p[1].job.version if p[1].job else 0),
        p[1].create_index))


@dataclasses.dataclass(slots=True)
class AllocPlaceResult:
    """One placement the scheduler must make (ref reconcile_util.go
    allocPlaceResult)."""
    name: str
    task_group: TaskGroup
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    canary: bool = False
    lost: bool = False
    downgrade_non_canary: bool = False
    min_job_version: int = 0


@dataclasses.dataclass(slots=True)
class AllocStopResult:
    alloc: Allocation
    client_status: str = ""
    status_description: str = ""
    follow_up_eval_id: str = ""


@dataclasses.dataclass(slots=True)
class AllocDestructiveResult:
    place_name: str
    place_task_group: TaskGroup
    stop_alloc: Allocation
    stop_status_description: str = "alloc is being updated due to job update"


@dataclasses.dataclass
class ReconcileResults:
    """ref reconcile.go reconcileResults"""
    deployment: Optional[Deployment] = None
    deployment_updates: list[DeploymentStatusUpdate] = dataclasses.field(
        default_factory=list)
    place: list[AllocPlaceResult] = dataclasses.field(default_factory=list)
    destructive_update: list[AllocDestructiveResult] = dataclasses.field(
        default_factory=list)
    inplace_update: list[Allocation] = dataclasses.field(default_factory=list)
    stop: list[AllocStopResult] = dataclasses.field(default_factory=list)
    attribute_updates: dict[str, Allocation] = dataclasses.field(
        default_factory=dict)
    desired_tg_updates: dict[str, DesiredUpdates] = dataclasses.field(
        default_factory=dict)
    desired_followup_evals: dict[str, list[Evaluation]] = dataclasses.field(
        default_factory=dict)


class AllocReconciler:
    """ref reconcile.go:40 allocReconciler"""

    def __init__(self, alloc_update_fn: Callable, batch: bool, job_id: str,
                 job: Optional[Job], deployment: Optional[Deployment],
                 existing_allocs: list[Allocation],
                 tainted_nodes: dict[str, Optional[Node]], eval_id: str,
                 eval_priority: int, now: float):
        self.alloc_update_fn = alloc_update_fn
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.deployment = deployment.copy() if deployment else None
        self.old_deployment: Optional[Deployment] = None
        self.existing_allocs = existing_allocs
        self.tainted = tainted_nodes
        self.eval_id = eval_id
        self.eval_priority = eval_priority
        self.now = now
        self.deployment_paused = False
        self.deployment_failed = False
        self.result = ReconcileResults()

    # ------------------------------------------------------------- compute

    def compute(self) -> ReconcileResults:
        """ref reconcile.go:189 Compute"""
        # parameterized/periodic PARENTS never place — children do. The
        # register path already skips eval creation for parents (ref
        # job_endpoint.go:365); treating a stray parent eval as stopped
        # makes that invariant defensive rather than upstream-only.
        stopped = self.job is None or self.job.stopped() or \
            self.job.is_parameterized() or self.job.is_periodic()
        if not stopped:
            self._cancel_unneeded_deployments()

        if self.deployment is not None:
            self.deployment_paused = self.deployment.status in (
                DEPLOYMENT_STATUS_PAUSED, DEPLOYMENT_STATUS_PENDING)
            self.deployment_failed = \
                self.deployment.status == DEPLOYMENT_STATUS_FAILED

        m = alloc_matrix(self.job if not stopped else None,
                         self.existing_allocs)

        if stopped:
            self._handle_stop(m)
            if self.deployment is not None and self.deployment.active():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=self.deployment.id,
                    status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description=DESC_DEPLOYMENT_CANCELLED))
            return self.result

        complete = True
        for group, allocs in m.items():
            if not self._compute_group(group, allocs):
                complete = False

        # deployment completion
        if self.deployment is not None and complete and \
           self.deployment.status == DEPLOYMENT_STATUS_RUNNING:
            self.result.deployment_updates.append(DeploymentStatusUpdate(
                deployment_id=self.deployment.id,
                status=DEPLOYMENT_STATUS_SUCCESSFUL,
                status_description="deployment completed successfully"))
        return self.result

    def _cancel_unneeded_deployments(self) -> None:
        """ref reconcile.go cancelUnneededDeployments"""
        d = self.deployment
        if d is None:
            return
        if d.job_version != self.job.version or \
           d.job_create_index != self.job.create_index:
            if d.active():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description=DESC_DEPLOYMENT_CANCELLED))
            self.old_deployment = d
            self.deployment = None
        elif not d.active():
            self.old_deployment = d
            self.deployment = None

    def _handle_stop(self, m: dict[str, AllocSet]) -> None:
        for group, allocs in m.items():
            desired = self.result.desired_tg_updates.setdefault(
                group, DesiredUpdates())
            untainted, migrate, lost = filter_by_tainted(allocs, self.tainted)
            live = filter_by_terminal(untainted)
            self._mark_stop(live, "", DESC_NOT_NEEDED)
            self._mark_stop(migrate, "", DESC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, DESC_NOT_NEEDED)
            desired.stop += len(live) + len(migrate) + len(lost)

    def _mark_stop(self, allocs: AllocSet, client_status: str,
                   desc: str) -> None:
        for alloc in allocs.values():
            self.result.stop.append(AllocStopResult(
                alloc=alloc, client_status=client_status,
                status_description=desc))

    def _mark_delayed(self, allocs: AllocSet, client_status: str, desc: str,
                      followup: dict[str, str]) -> None:
        for alloc in allocs.values():
            self.result.stop.append(AllocStopResult(
                alloc=alloc, client_status=client_status,
                status_description=desc,
                follow_up_eval_id=followup.get(alloc.id, "")))

    # ------------------------------------------------------ per-group logic

    def _compute_group(self, group: str, all_allocs: AllocSet) -> bool:
        """ref reconcile.go:346 computeGroup"""
        desired = self.result.desired_tg_updates.setdefault(
            group, DesiredUpdates())
        tg = self.job.lookup_task_group(group)

        if tg is None:
            # group removed: stop everything
            untainted, migrate, lost = filter_by_tainted(all_allocs, self.tainted)
            live = filter_by_terminal(untainted)
            self._mark_stop(live, "", DESC_NOT_NEEDED)
            self._mark_stop(migrate, "", DESC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, DESC_NOT_NEEDED)
            desired.stop += len(live) + len(migrate) + len(lost)
            return True

        # deployment state for the group
        dstate: Optional[DeploymentState] = None
        existing_deployment = False
        if self.deployment is not None and group in self.deployment.task_groups:
            dstate = self.deployment.task_groups[group]
            existing_deployment = True
        if not existing_deployment:
            dstate = DeploymentState()
            if tg.update is not None:
                dstate.auto_revert = tg.update.auto_revert
                dstate.auto_promote = tg.update.auto_promote
                dstate.progress_deadline_sec = tg.update.progress_deadline_sec

        # old terminal batch allocs are ignored
        all_allocs, ignored = self._filter_old_terminal_allocs(all_allocs)
        desired.ignore += len(ignored)

        canaries, all_allocs = self._handle_group_canaries(all_allocs,
                                                           desired, tg)

        untainted, migrate, lost = filter_by_tainted(all_allocs, self.tainted)

        # graceful client disconnection (ref 1.3 reconcile_util.go
        # disconnecting/reconnecting + reconcile.go reconcileReconnecting):
        # with max_client_disconnect, a running alloc on a down node rides
        # out the window as `unknown` (replacement placed alongside);
        # if the client returns inside the window the original wins and
        # the replacement stops.
        disconnecting, lost = split_disconnecting(tg, lost, self.now)
        reconnecting, untainted = split_reconnecting(untainted)
        self._handle_disconnecting(tg, group, disconnecting)
        untainted = self._handle_reconnecting(tg, group, reconnecting,
                                              untainted)

        untainted, reschedule_now, reschedule_later = filter_by_rescheduleable(
            untainted, self.batch, self.now, self.eval_id, self.deployment)

        lost_later = delay_by_stop_after_client_disconnect(lost)
        lost_later_evals = self._create_timeout_later_evals(lost_later, group)

        self._handle_delayed_reschedules(reschedule_later, group)

        # name-slot membership as a fixed-shape masked tensor (ISSUE 15):
        # the twin's selection ops are field-exact with AllocNameIndex
        # (fuzz-pinned); NOMAD_RECONCILE_TENSOR=0 restores the set walk
        from .reconcile_tensor import make_name_index
        name_index = make_name_index(
            self.job_id, group, tg.count,
            union(untainted, migrate, reschedule_now, lost))

        canary_state = (dstate is not None and dstate.desired_canaries != 0
                        and not dstate.promoted)
        stop = self._compute_stop(tg, name_index, untainted, migrate, lost,
                                  canaries, canary_state, lost_later_evals)
        desired.stop += len(stop)
        untainted = difference(untainted, stop)

        ignore, inplace, destructive = self._compute_updates(tg, untainted)
        desired.ignore += len(ignore)
        desired.in_place_update += len(inplace)
        if not existing_deployment:
            dstate.desired_total += len(destructive) + len(inplace)

        if canary_state:
            untainted = difference(untainted, canaries)

        # canary requirement
        strategy = tg.update
        canaries_promoted = dstate is not None and dstate.promoted
        require_canary = (len(destructive) != 0 and strategy is not None and
                          strategy.canary > 0 and
                          len(canaries) < strategy.canary and
                          not canaries_promoted)
        if require_canary:
            dstate.desired_canaries = strategy.canary
        if require_canary and not self.deployment_paused and \
           not self.deployment_failed:
            number = strategy.canary - len(canaries)
            desired.canary += number
            for nm in name_index.next_canaries(number, canaries, destructive):
                self.result.place.append(AllocPlaceResult(
                    name=nm, canary=True, task_group=tg))

        canary_state = (dstate is not None and dstate.desired_canaries != 0
                        and not dstate.promoted)
        limit = self._compute_limit(tg, untainted, destructive, migrate,
                                    canary_state)

        place: list[AllocPlaceResult] = []
        if len(lost_later) == 0:
            place = self._compute_placements(
                tg, name_index, untainted, migrate, reschedule_now,
                canary_state, lost)
            if not existing_deployment:
                dstate.desired_total += len(place)

        deployment_place_ready = (not self.deployment_paused and
                                  not self.deployment_failed and
                                  not canary_state)
        if deployment_place_ready:
            desired.place += len(place)
            self.result.place.extend(place)
            self._mark_stop(reschedule_now, "", DESC_RESCHEDULED)
            desired.stop += len(reschedule_now)
            limit -= min(len(place), limit)
        else:
            if lost:
                allowed = min(len(lost), len(place))
                desired.place += allowed
                self.result.place.extend(place[:allowed])
            if reschedule_now:
                for p in place:
                    prev = p.previous_alloc
                    if p.reschedule and not (
                            self.deployment_failed and prev is not None and
                            self.deployment is not None and
                            self.deployment.id == prev.deployment_id):
                        self.result.place.append(p)
                        desired.place += 1
                        self.result.stop.append(AllocStopResult(
                            alloc=prev, status_description=DESC_RESCHEDULED))
                        desired.stop += 1

        if deployment_place_ready:
            n = min(len(destructive), limit)
            desired.destructive_update += n
            desired.ignore += len(destructive) - n
            for alloc in name_order(destructive)[:n]:
                self.result.destructive_update.append(AllocDestructiveResult(
                    place_name=alloc.name, place_task_group=tg,
                    stop_alloc=alloc))
        else:
            desired.ignore += len(destructive)

        # migrations
        desired.migrate += len(migrate)
        for alloc in name_order(migrate):
            self.result.stop.append(AllocStopResult(
                alloc=alloc, status_description=DESC_MIGRATING))
            self.result.place.append(AllocPlaceResult(
                name=alloc.name, task_group=tg, previous_alloc=alloc,
                canary=(alloc.deployment_status.canary
                        if alloc.deployment_status else False),
                downgrade_non_canary=(canary_state and not (
                    alloc.deployment_status and alloc.deployment_status.canary)),
                min_job_version=alloc.job.version if alloc.job else 0))

        # create deployment if needed
        updating_spec = bool(destructive) or bool(self.result.inplace_update)
        had_running = any(
            a.job is not None and a.job.version == self.job.version and
            a.job.create_index == self.job.create_index
            for a in all_allocs.values())
        if not existing_deployment and strategy is not None and \
           strategy.rolling() and dstate.desired_total != 0 and \
           (not had_running or updating_spec):
            if self.deployment is None:
                self.deployment = new_deployment(self.job, self.now)
                self.result.deployment = self.deployment
            self.deployment.task_groups[group] = dstate

        complete = (len(destructive) + len(inplace) + len(place) +
                    len(migrate) + len(reschedule_now) +
                    len(reschedule_later) == 0 and not require_canary)
        if complete and self.deployment is not None:
            ds = self.deployment.task_groups.get(group)
            if ds is not None:
                if ds.healthy_allocs < max(ds.desired_total,
                                           ds.desired_canaries) or \
                   (ds.desired_canaries > 0 and not ds.promoted):
                    complete = False
        return complete

    # ---------------------------------------------------------- sub-steps

    def _filter_old_terminal_allocs(self, all_allocs: AllocSet
                                    ) -> tuple[AllocSet, AllocSet]:
        """ref reconcile.go filterOldTerminalAllocs (batch only)"""
        if not self.batch:
            return all_allocs, {}
        filtered = dict(all_allocs)
        ignored: AllocSet = {}
        for aid, alloc in list(filtered.items()):
            older = (alloc.job is not None and
                     (alloc.job.version < self.job.version or
                      alloc.job.create_index < self.job.create_index))
            if older and alloc.terminal_status():
                del filtered[aid]
                ignored[aid] = alloc
        return filtered, ignored

    def _handle_group_canaries(self, all_allocs: AllocSet,
                               desired: DesiredUpdates, tg
                               ) -> tuple[AllocSet, AllocSet]:
        """ref reconcile.go handleGroupCanaries"""
        stop_ids: list[str] = []
        if self.old_deployment is not None:
            for ds in self.old_deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        if self.deployment is not None and \
           self.deployment.status == DEPLOYMENT_STATUS_FAILED:
            for ds in self.deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        stop_set = from_keys(all_allocs, stop_ids)
        self._mark_stop(stop_set, "", DESC_NOT_NEEDED)
        desired.stop += len(stop_set)
        all_allocs = difference(all_allocs, stop_set)

        canaries: AllocSet = {}
        if self.deployment is not None:
            canary_ids: list[str] = []
            for ds in self.deployment.task_groups.values():
                canary_ids.extend(ds.placed_canaries)
            canaries = from_keys(all_allocs, canary_ids)
            untainted, migrate, lost = filter_by_tainted(canaries, self.tainted)
            # 1.3 analog: a canary on a disconnected node rides the
            # max_client_disconnect window like any other alloc — it is
            # LEFT in the group set so the disconnect split marks it
            # unknown, and its absence from `canaries` makes the canary
            # top-up place a replacement; on reconnect the generic
            # name-slot logic stops the replacement.
            _disconnecting, lost = split_disconnecting(tg, lost, self.now)
            self._mark_stop(migrate, "", DESC_MIGRATING)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, "alloc lost")
            canaries = untainted
            all_allocs = difference(all_allocs, migrate, lost)
        return canaries, all_allocs

    def _compute_limit(self, tg: TaskGroup, untainted: AllocSet,
                       destructive: AllocSet, migrate: AllocSet,
                       canary_state: bool) -> int:
        """ref reconcile.go:671 computeLimit"""
        if tg.update is None or not tg.update.rolling() or \
           len(destructive) + len(migrate) == 0:
            return tg.count
        if self.deployment_paused or self.deployment_failed:
            return 0
        if canary_state:
            return 0
        limit = tg.update.max_parallel
        if self.deployment is not None:
            part_of, _ = filter_by_deployment(untainted, self.deployment.id)
            for alloc in part_of.values():
                if alloc.deployment_status is not None and \
                   alloc.deployment_status.is_unhealthy():
                    return 0
                if not (alloc.deployment_status is not None and
                        alloc.deployment_status.is_healthy()):
                    limit -= 1
        return max(0, limit)

    def _compute_placements(self, tg: TaskGroup, name_index: AllocNameIndex,
                            untainted: AllocSet, migrate: AllocSet,
                            reschedule: AllocSet, canary_state: bool,
                            lost: AllocSet) -> list[AllocPlaceResult]:
        """ref reconcile.go:717 computePlacements"""
        place: list[AllocPlaceResult] = []
        for alloc in reschedule.values():
            place.append(AllocPlaceResult(
                name=alloc.name, task_group=tg, previous_alloc=alloc,
                reschedule=True,
                canary=(alloc.deployment_status.canary
                        if alloc.deployment_status else False),
                downgrade_non_canary=(canary_state and not (
                    alloc.deployment_status and alloc.deployment_status.canary)),
                min_job_version=alloc.job.version if alloc.job else 0))
        existing = len(untainted) + len(migrate) + len(reschedule)
        # a lost alloc's name slot may ALREADY be covered: an unknown
        # original that rode the max_client_disconnect window got a
        # same-name replacement placed beside it — when it finally goes
        # lost (window expiry / repeat node-down), replacing it again
        # would double-fill the slot (two live non-canary holders). Only
        # possible through the 1.3 disconnect flow; plain lost names are
        # never held by untainted allocs.
        held = {a.name for s in (untainted, migrate, reschedule)
                for a in s.values()}
        for alloc in lost.values():
            if existing >= tg.count:
                break
            if alloc.name in held:
                continue
            existing += 1
            place.append(AllocPlaceResult(
                name=alloc.name, task_group=tg, previous_alloc=alloc,
                reschedule=False, lost=True,
                canary=(alloc.deployment_status.canary
                        if alloc.deployment_status else False),
                downgrade_non_canary=(canary_state and not (
                    alloc.deployment_status and alloc.deployment_status.canary)),
                min_job_version=alloc.job.version if alloc.job else 0))
        if existing < tg.count:
            # fresh slots are uniform except for the name: batch-stamp
            # them (a 50k-instance job mints 50k results here — dataclass
            # __init__ frames were a visible slice of reconcile)
            from ..structs.fastbatch import stamp_batch
            names = name_index.next(tg.count - existing)
            place.extend(stamp_batch(
                AllocPlaceResult, len(names),
                shared={"task_group": tg,
                        "downgrade_non_canary": canary_state},
                varying={"name": names}))
        return place

    def _compute_stop(self, tg: TaskGroup, name_index: AllocNameIndex,
                      untainted: AllocSet, migrate: AllocSet, lost: AllocSet,
                      canaries: AllocSet, canary_state: bool,
                      followup_evals: dict[str, str]) -> AllocSet:
        """ref reconcile.go:777 computeStop"""
        stop: AllocSet = {}
        stop.update(lost)
        self._mark_delayed(lost, ALLOC_CLIENT_LOST, "alloc lost",
                           followup_evals)

        if canary_state:
            untainted = difference(untainted, canaries)

        # convergent duplicate-name cleanup: historical churn (disconnect
        # replacements, same-pass reconnects, lost-of-unknown) can leave
        # two live holders of one name slot even when the total is within
        # count — and once present, a duplicate self-propagates (each
        # holder gets its own migrate/lost replacement). Stop the extras
        # (keep highest job version, then the earliest-created) so every
        # pass strictly reduces duplication; the freed coverage is placed
        # under a FRESH name by computePlacements.
        by_name: dict = {}
        for aid, alloc in untainted.items():
            by_name.setdefault(alloc.name, []).append((aid, alloc))
        dups = [g for g in by_name.values() if len(g) > 1]
        if dups:
            untainted = dict(untainted)
            for group in dups:
                for aid, alloc in _rank_name_slot_holders(group)[1:]:
                    if alloc.terminal_status():
                        continue
                    stop[aid] = alloc
                    self.result.stop.append(AllocStopResult(
                        alloc=alloc, status_description=DESC_DUP_NAME))
                    untainted.pop(aid, None)

        remove = len(untainted) + len(migrate) - tg.count
        if remove <= 0:
            return stop

        untainted = filter_by_terminal(untainted)

        # prefer stopping duplicates of promoted canary names
        if not canary_state and canaries:
            canary_names = name_set(canaries)
            for aid, alloc in list(difference(untainted, canaries).items()):
                if alloc.name in canary_names:
                    stop[aid] = alloc
                    self.result.stop.append(AllocStopResult(
                        alloc=alloc, status_description=DESC_NOT_NEEDED))
                    untainted.pop(aid, None)
                    remove -= 1
                    if remove == 0:
                        return stop

        # prefer stopping migrating allocs
        if migrate:
            from .reconcile_tensor import make_name_index
            m_index = make_name_index(self.job_id, tg.name, tg.count,
                                      dict(migrate))
            remove_names = m_index.highest(remove)
            for aid, alloc in list(migrate.items()):
                if alloc.name not in remove_names:
                    continue
                self.result.stop.append(AllocStopResult(
                    alloc=alloc, status_description=DESC_NOT_NEEDED))
                migrate.pop(aid)
                stop[aid] = alloc
                from ..structs import alloc_name_index as _ani
                name_index.unset_index(_ani(alloc.name))
                remove -= 1
                if remove == 0:
                    return stop

        # stop highest-indexed names
        remove_names = name_index.highest(remove)
        for aid, alloc in list(untainted.items()):
            if alloc.name in remove_names:
                stop[aid] = alloc
                self.result.stop.append(AllocStopResult(
                    alloc=alloc, status_description=DESC_NOT_NEEDED))
                untainted.pop(aid)
                remove -= 1
                if remove == 0:
                    return stop

        # duplicate names fallback
        for aid, alloc in list(untainted.items()):
            stop[aid] = alloc
            self.result.stop.append(AllocStopResult(
                alloc=alloc, status_description=DESC_NOT_NEEDED))
            untainted.pop(aid)
            remove -= 1
            if remove == 0:
                return stop
        return stop

    def _compute_updates(self, tg: TaskGroup, untainted: AllocSet
                         ) -> tuple[AllocSet, AllocSet, AllocSet]:
        """ref reconcile.go:887 computeUpdates"""
        ignore: AllocSet = {}
        inplace: AllocSet = {}
        destructive: AllocSet = {}
        for aid, alloc in untainted.items():
            ignore_change, destructive_change, inplace_alloc = \
                self.alloc_update_fn(alloc, self.job, tg)
            if ignore_change:
                ignore[aid] = alloc
            elif destructive_change:
                destructive[aid] = alloc
            else:
                inplace[aid] = alloc
                if inplace_alloc is not None:
                    self.result.inplace_update.append(inplace_alloc)
        return ignore, inplace, destructive

    def _handle_delayed_reschedules(self, infos: list[DelayedRescheduleInfo],
                                    tg_name: str) -> None:
        """Batched follow-up evals for later reschedules
        (ref reconcile.go:911 handleDelayedReschedules)."""
        self._create_followup_evals(infos, tg_name, mark_followup=True)

    def _create_timeout_later_evals(self, infos: list[DelayedRescheduleInfo],
                                    tg_name: str,
                                    trigger: str = TRIGGER_FAILED_FOLLOW_UP
                                    ) -> dict[str, str]:
        return self._create_followup_evals(infos, tg_name,
                                           mark_followup=False,
                                           trigger=trigger)

    # ------------------------------------ graceful client disconnection

    def _handle_disconnecting(self, tg, group: str,
                              disconnecting: dict) -> None:
        """Mark newly-disconnected allocs `unknown` (plan attribute
        update stamping disconnected_at) and schedule the expiry eval
        that turns them lost if the client never returns (ref 1.3
        reconcile.go appendUnknownUpdates + createTimeoutLaterEvals)."""
        if not disconnecting:
            return
        window = tg.max_client_disconnect_sec or 0.0
        infos = []
        for aid, alloc in disconnecting.items():
            since = alloc.disconnected_at
            if alloc.client_status != ALLOC_CLIENT_UNKNOWN or not since:
                updated = alloc.copy()
                updated.client_status = ALLOC_CLIENT_UNKNOWN
                updated.client_description = DESC_UNKNOWN
                updated.disconnected_at = since = self.now
                self.result.attribute_updates[aid] = updated
                # expiry eval only on the FIRST (marking) pass —
                # re-evals during the window would pile up duplicates
                infos.append(DelayedRescheduleInfo(
                    alloc_id=aid, alloc=alloc,
                    reschedule_time=since + window))
        self._create_timeout_later_evals(infos, group,
                                         trigger=TRIGGER_MAX_DISCONNECT)
        desired = self.result.desired_tg_updates.setdefault(
            group, DesiredUpdates())
        desired.ignore += len(disconnecting)

    def _handle_reconnecting(self, tg, group: str, reconnecting: dict,
                             untainted: dict) -> dict:
        """The client returned: inside the window the ORIGINAL alloc
        wins its name slot back and any replacement stops; PAST the
        window the original is expired — it stops and the replacement
        keeps the slot (ref 1.3 reconcile.go reconcileReconnecting,
        which stops Expired originals rather than churning the workload
        back onto a flapping node)."""
        if not reconnecting:
            return untainted
        desired = self.result.desired_tg_updates.setdefault(
            group, DesiredUpdates())
        window = tg.max_client_disconnect_sec or 0.0
        fresh: dict = {}
        for aid, alloc in reconnecting.items():
            since = alloc.disconnected_at
            if since and self.now >= since + window:
                # reconnected too late: the replacement won
                self.result.stop.append(AllocStopResult(
                    alloc=alloc, client_status=ALLOC_CLIENT_LOST,
                    status_description=DESC_RECONNECT_EXPIRED))
                desired.stop += 1
            elif alloc.job is not None and self.job is not None and (
                    alloc.job.version < self.job.version or
                    alloc.job.create_index < self.job.create_index):
                # the job was UPDATED while the client was away: the
                # stale original stops and the (newer-version)
                # replacement keeps the slot — restoring the original
                # would mislabel old task config as the new version,
                # since placements/updates normalize alloc.job to the
                # plan job (ref reconcileReconnecting: reconnecting
                # allocs needing an update are stopped, newer pickers
                # keep the highest job version)
                self.result.stop.append(AllocStopResult(
                    alloc=alloc,
                    status_description=DESC_RECONNECT_OUTDATED))
                desired.stop += 1
            else:
                fresh[aid] = alloc
        # the original AND its window-replacement can both have gone
        # unknown (second node-down) and reconnect in the SAME pass —
        # each looks like "the original", so without a per-name pick
        # both restore and double-fill the slot. Keep one per name:
        # highest job version, then the earliest-created (the true
        # original) — the reference's reconnecting picker default.
        by_name: dict = {}
        for aid, alloc in fresh.items():
            by_name.setdefault(alloc.name, []).append((aid, alloc))
        for name, group in by_name.items():
            if len(group) == 1:
                continue
            for aid, alloc in _rank_name_slot_holders(group)[1:]:
                self.result.stop.append(AllocStopResult(
                    alloc=alloc, status_description=DESC_RECONNECTED))
                desired.stop += 1
                del fresh[aid]
        originals_by_name = {a.name: aid for aid, a in fresh.items()}
        for aid, alloc in list(untainted.items()):
            orig = originals_by_name.get(alloc.name)
            if orig is None or aid == orig or \
                    alloc.server_terminal_status():
                # already desired-stop needs nothing; a client-FAILED
                # replacement still needs the stop so it can't flow into
                # reschedule_now beside the reconnected original (ref
                # gates on ServerTerminalStatus)
                continue
            # a replacement placed during the disconnect: stop it
            self.result.stop.append(AllocStopResult(
                alloc=alloc, client_status="",
                status_description=DESC_RECONNECTED))
            desired.stop += 1
            del untainted[aid]
        for aid, alloc in fresh.items():
            # flip back to running: the client's change-driven sync won't
            # re-push an unchanged status, and the alloc was running when
            # it went unknown (a task that actually died surfaces as a
            # NEW failed update, which does sync)
            updated = alloc.copy()
            updated.client_status = ALLOC_CLIENT_RUNNING
            updated.client_description = DESC_RECONNECT_OK
            updated.disconnected_at = 0.0
            self.result.attribute_updates[aid] = updated
            untainted[aid] = updated
        return untainted

    def _create_followup_evals(self, infos: list[DelayedRescheduleInfo],
                               tg_name: str, mark_followup: bool,
                               trigger: str = TRIGGER_FAILED_FOLLOW_UP
                               ) -> dict[str, str]:
        if not infos:
            return {}
        infos = sorted(infos, key=lambda i: i.reschedule_time)
        # batch into 5s windows (ref batchedFailedAllocWindowSize)
        window = 5.0
        evals: list[Evaluation] = []
        alloc_to_eval: dict[str, str] = {}
        cur_eval: Optional[Evaluation] = None
        cur_end = 0.0
        for info in infos:
            if cur_eval is None or info.reschedule_time > cur_end:
                cur_eval = Evaluation(
                    namespace=self.job.namespace if self.job else "default",
                    priority=self.eval_priority,
                    type=self.job.type if self.job else "service",
                    triggered_by=trigger,
                    job_id=self.job_id,
                    status=EVAL_STATUS_PENDING,
                    wait_until_unix=info.reschedule_time)
                cur_end = info.reschedule_time + window
                evals.append(cur_eval)
            alloc_to_eval[info.alloc_id] = cur_eval.id
        self.result.desired_followup_evals.setdefault(tg_name, []).extend(evals)
        if mark_followup:
            for info in infos:
                updated = info.alloc.copy()
                updated.follow_up_eval_id = alloc_to_eval[info.alloc_id]
                self.result.attribute_updates[updated.id] = updated
        return alloc_to_eval
