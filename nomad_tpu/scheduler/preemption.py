"""Preemption: distance-based victim selection grouped by priority bands
(ref scheduler/preemption.go:96 Preemptor, PreemptForTaskGroup:198,
PreemptForNetwork:270, PreemptForDevice:472, distance fns:608-661).

The TPU analog is masked iterative top-k over the same distance metric
(SURVEY.md hard part 4); this host version is the oracle.
"""
from __future__ import annotations

import math
from typing import Optional

from ..structs import (
    AllocatedResources, Allocation, NetworkIndex, Node, allocs_fit,
)


class Preemptor:
    def __init__(self, job_priority: int, ctx, job_id: str):
        self.job_priority = job_priority
        self.ctx = ctx
        self.job_id = job_id
        self.node: Optional[Node] = None
        self.current_preemptions: list[Allocation] = []
        self.candidates: list[Allocation] = []

    def set_node(self, node: Node) -> None:
        self.node = node

    def set_preemptions(self, allocs: list[Allocation]) -> None:
        self.current_preemptions = list(allocs)

    def set_candidates(self, allocs: list[Allocation]) -> None:
        """Keep only allocs with strictly lower priority that aren't already
        being preempted in this plan (ref preemption.go
        filterAndGroupPreemptibleAllocs)."""
        preempted_ids = {a.id for a in self.current_preemptions}
        self.candidates = []
        for a in allocs:
            prio = a.job.priority if a.job else 50
            if prio >= self.job_priority:
                continue
            if a.id in preempted_ids:
                continue
            self.candidates.append(a)

    # ---- task-group resources (ref preemption.go:198) ----

    def preempt_for_task_group(self, ask: AllocatedResources
                               ) -> list[Allocation]:
        """Greedy victim selection: lowest priority band first, then minimal
        resource distance; stop when the ask fits."""
        if self.node is None or not self.candidates:
            return []
        ask_alloc = Allocation(allocated_resources=ask)
        # lowest priority band first; within a band, the alloc whose resources
        # are closest to the ask (minimal over-preemption)
        remaining = sorted(
            self.candidates,
            key=lambda a: ((a.job.priority if a.job else 50),
                           _resource_distance(a, ask)))
        victims: list[Allocation] = []
        base = [a for a in self.ctx.proposed_allocs(self.node.id)]
        victim_ids: set[str] = set()
        for candidate in remaining:
            current = [a for a in base if a.id not in victim_ids] + [ask_alloc]
            fit, _, _ = allocs_fit(self.node, current)
            if fit:
                break
            victims.append(candidate)
            victim_ids.add(candidate.id)
        else:
            current = [a for a in base if a.id not in victim_ids] + [ask_alloc]
            fit, _, _ = allocs_fit(self.node, current)
            if not fit:
                return []
        if not victims:
            return []
        # Eliminate unnecessary victims (ref preemption.go
        # eliminateSuperSetAllocations): try adding back from highest priority
        for candidate in sorted(victims,
                                key=lambda a: -(a.job.priority if a.job else 50)):
            trial_ids = victim_ids - {candidate.id}
            current = [a for a in base if a.id not in trial_ids] + [ask_alloc]
            fit, _, _ = allocs_fit(self.node, current)
            if fit:
                victim_ids = trial_ids
        return [v for v in victims if v.id in victim_ids]

    # ---- network (ref preemption.go:270) ----

    def preempt_for_network(self, ask, net_idx: NetworkIndex
                            ) -> Optional[list[Allocation]]:
        """Find victims whose removal frees the ports/bandwidth the ask needs."""
        if self.node is None or not self.candidates:
            return None
        needed_ports = {p.value for p in ask.reserved_ports}
        needed_mbits = ask.mbits

        def uses_needed(alloc: Allocation) -> tuple[bool, int]:
            mbits = 0
            hits = False
            res = alloc.allocated_resources
            nets = list(res.shared.networks)
            for tr in res.tasks.values():
                nets.extend(tr.networks)
            for net in nets:
                mbits += net.mbits
                for p in list(net.reserved_ports) + list(net.dynamic_ports):
                    if p.value in needed_ports:
                        hits = True
            return hits, mbits

        scored = []
        for a in self.candidates:
            hits, mbits = uses_needed(a)
            prio = a.job.priority if a.job else 50
            scored.append((not hits, prio, -mbits, a))
        scored.sort(key=lambda t: t[:3])

        victims: list[Allocation] = []
        victim_ids: set[str] = set()
        base = self.ctx.proposed_allocs(self.node.id)
        for _, _, _, candidate in scored:
            victims.append(candidate)
            victim_ids.add(candidate.id)
            idx = NetworkIndex()
            idx.set_node(self.node)
            idx.add_allocs([a for a in base if a.id not in victim_ids])
            offer, err = idx.assign_network(ask)
            if offer is not None:
                return victims
            if needed_mbits == 0 and not needed_ports and len(victims) >= 3:
                break
        return None

    # ---- devices (ref preemption.go:472) ----

    def preempt_for_device(self, ask, dev_allocator) -> Optional[list[Allocation]]:
        if self.node is None or not self.candidates:
            return None
        holders = []
        for a in self.candidates:
            for tr in a.allocated_resources.tasks.values():
                for d in tr.devices:
                    holders.append((a.job.priority if a.job else 50,
                                    len(d.device_ids), a))
                    break
        holders.sort(key=lambda t: (t[0], -t[1]))
        victims, count = [], 0
        seen = set()
        for _, n, a in holders:
            if a.id in seen:
                continue
            seen.add(a.id)
            victims.append(a)
            count += n
            if count >= ask.count:
                return victims
        return None


def _resource_distance(alloc: Allocation, ask: AllocatedResources) -> float:
    """Normalized euclidean distance between an alloc's resources and the ask
    (ref preemption.go:608 basicResourceDistance)."""
    a = alloc.comparable_resources()
    b = Allocation(allocated_resources=ask).comparable_resources()
    dims = 0
    total = 0.0
    if b.cpu_shares > 0:
        total += ((a.cpu_shares - b.cpu_shares) / b.cpu_shares) ** 2
        dims += 1
    if b.memory_mb > 0:
        total += ((a.memory_mb - b.memory_mb) / b.memory_mb) ** 2
        dims += 1
    if b.disk_mb > 0:
        total += ((a.disk_mb - b.disk_mb) / b.disk_mb) ** 2
        dims += 1
    if dims == 0:
        return 0.0
    return math.sqrt(total / dims)
