"""Reconciler set algebra (ref scheduler/reconcile_util.go): allocSet
filters and the alloc-name index."""
from __future__ import annotations

import dataclasses
from typing import Optional

from ..structs import (
    Allocation, Deployment, Job, Node, TaskGroup,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_RUNNING, ALLOC_CLIENT_UNKNOWN,
    ALLOC_DESIRED_EVICT, ALLOC_DESIRED_STOP, alloc_name, alloc_name_index,
)

AllocSet = dict[str, Allocation]

# Window within which a future reschedule time counts as "now"
# (ref reconcile.go rescheduleWindowSize = 1s... actually util)
RESCHEDULE_WINDOW_SEC = 5.0


def alloc_matrix(job: Optional[Job], allocs: list[Allocation]
                 ) -> dict[str, AllocSet]:
    """Group allocs by task group, seeding groups from the job
    (ref reconcile_util.go:107 newAllocMatrix)."""
    m: dict[str, AllocSet] = {}
    for a in allocs:
        m.setdefault(a.task_group, {})[a.id] = a
    if job is not None:
        for tg in job.task_groups:
            m.setdefault(tg.name, {})
    return m


def difference(a: AllocSet, *others: AllocSet) -> AllocSet:
    out = dict(a)
    for o in others:
        for k in o:
            out.pop(k, None)
    return out


def union(*sets: AllocSet) -> AllocSet:
    out: AllocSet = {}
    for s in sets:
        out.update(s)
    return out


def from_keys(a: AllocSet, keys) -> AllocSet:
    return {k: a[k] for k in keys if k in a}


def name_set(a: AllocSet) -> set[str]:
    return {alloc.name for alloc in a.values()}


def name_order(a: AllocSet) -> list[Allocation]:
    return sorted(a.values(), key=lambda x: x.name)


def filter_by_terminal(a: AllocSet) -> AllocSet:
    """Remove terminal allocs (ref reconcile_util.go filterByTerminal)."""
    return {k: v for k, v in a.items() if not v.terminal_status()}


def filter_by_tainted(a: AllocSet, tainted: dict[str, Optional[Node]]
                      ) -> tuple[AllocSet, AllocSet, AllocSet]:
    """(untainted, migrate, lost) — ref reconcile_util.go:217."""
    untainted: AllocSet = {}
    migrate: AllocSet = {}
    lost: AllocSet = {}
    for aid, alloc in a.items():
        if alloc.terminal_status():
            untainted[aid] = alloc
            continue
        if alloc.desired_transition.should_migrate():
            migrate[aid] = alloc
            continue
        if alloc.node_id not in tainted:
            untainted[aid] = alloc
            continue
        node = tainted[alloc.node_id]
        if node is None or node.terminal_status():
            lost[aid] = alloc
            continue
        untainted[aid] = alloc
    return untainted, migrate, lost


def split_disconnecting(tg, lost: AllocSet, now: float
                        ) -> tuple[AllocSet, AllocSet]:
    """(disconnecting, still_lost) — graceful client disconnection (ref
    1.3 reconcile_util.go filterByTainted 'disconnecting' + Allocation.
    Expired): with max_client_disconnect set, a running alloc on a down
    node rides out the window as `unknown` instead of being lost."""
    window = getattr(tg, "max_client_disconnect_sec", None)
    if not window:
        return {}, lost
    disconnecting: AllocSet = {}
    still_lost: AllocSet = {}
    for aid, alloc in lost.items():
        # only RUNNING work rides out the window: a pending alloc (tasks
        # never started) reschedules normally, and restoring it to
        # "running" on reconnect would misstate its health
        if alloc.client_status not in (ALLOC_CLIENT_RUNNING,
                                       ALLOC_CLIENT_UNKNOWN):
            still_lost[aid] = alloc
            continue
        since = alloc.disconnected_at or now
        if now < since + window:
            disconnecting[aid] = alloc
        else:
            still_lost[aid] = alloc          # window expired -> lost
    return disconnecting, still_lost


def split_reconnecting(untainted: AllocSet) -> tuple[AllocSet, AllocSet]:
    """(reconnecting, rest) — allocs still marked `unknown` whose node is
    no longer tainted: the client came back inside the window (ref 1.3
    reconcile.go reconcileReconnecting)."""
    reconnecting: AllocSet = {}
    rest: AllocSet = {}
    for aid, alloc in untainted.items():
        if alloc.client_status == ALLOC_CLIENT_UNKNOWN and \
                not alloc.server_terminal_status():
            reconnecting[aid] = alloc
        else:
            rest[aid] = alloc
    return reconnecting, rest


def should_filter(alloc: Allocation, is_batch: bool) -> tuple[bool, bool]:
    """(untainted, ignore) — ref reconcile_util.go shouldFilter."""
    if is_batch:
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            if alloc.ran_successfully():
                return True, False
            return False, True
        if alloc.client_status != ALLOC_CLIENT_FAILED:
            return True, False
        return False, False
    # service
    if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
        return False, True
    if alloc.client_status in (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_LOST):
        return False, True
    return False, False


def update_by_reschedulable(alloc: Allocation, now: float, eval_id: str,
                            deployment: Optional[Deployment]
                            ) -> tuple[bool, bool, float]:
    """(reschedule_now, reschedule_later, when) — ref reconcile_util.go
    updateByReschedulable."""
    if deployment is not None and alloc.deployment_id == deployment.id and \
       deployment.active() and not alloc.desired_transition.should_migrate() \
       and not bool(alloc.desired_transition.reschedule):
        return False, False, 0.0
    now_flag = False
    if alloc.desired_transition.should_force_reschedule():
        now_flag = True
    when, eligible = alloc.next_reschedule_time()
    if eligible and (alloc.follow_up_eval_id == eval_id or
                     when - now <= RESCHEDULE_WINDOW_SEC):
        return True, False, when
    if now_flag:
        return True, False, now
    if eligible and not alloc.follow_up_eval_id:
        return False, True, when
    return False, False, 0.0


@dataclasses.dataclass
class DelayedRescheduleInfo:
    alloc_id: str
    alloc: Allocation
    reschedule_time: float


def filter_by_rescheduleable(a: AllocSet, is_batch: bool, now: float,
                             eval_id: str, deployment: Optional[Deployment]
                             ) -> tuple[AllocSet, AllocSet,
                                        list[DelayedRescheduleInfo]]:
    """(untainted, reschedule_now, reschedule_later) — ref
    reconcile_util.go:257."""
    untainted: AllocSet = {}
    reschedule_now: AllocSet = {}
    reschedule_later: list[DelayedRescheduleInfo] = []
    for aid, alloc in a.items():
        # already replaced
        if alloc.next_allocation and alloc.terminal_status():
            continue
        is_untainted, ignore = should_filter(alloc, is_batch)
        if is_untainted:
            untainted[aid] = alloc
        if is_untainted or ignore:
            continue
        now_flag, later_flag, when = update_by_reschedulable(
            alloc, now, eval_id, deployment)
        if now_flag:
            reschedule_now[aid] = alloc
        else:
            untainted[aid] = alloc
            if later_flag:
                reschedule_later.append(DelayedRescheduleInfo(aid, alloc, when))
    return untainted, reschedule_now, reschedule_later


def filter_by_deployment(a: AllocSet, deployment_id: str
                         ) -> tuple[AllocSet, AllocSet]:
    """(part of deployment, not part) — ref reconcile_util.go."""
    match: AllocSet = {}
    nonmatch: AllocSet = {}
    for aid, alloc in a.items():
        if alloc.deployment_id == deployment_id:
            match[aid] = alloc
        else:
            nonmatch[aid] = alloc
    return match, nonmatch


def delay_by_stop_after_client_disconnect(lost: AllocSet
                                          ) -> list[DelayedRescheduleInfo]:
    """Lost allocs whose group sets stop_after_client_disconnect get a delayed
    stop instead of an immediate one (ref reconcile_util.go)."""
    out = []
    for alloc in lost.values():
        if alloc.job is None:
            continue
        tg = alloc.job.lookup_task_group(alloc.task_group)
        if tg is None or tg.stop_after_client_disconnect_sec is None:
            continue
        when = alloc.last_event_time() + tg.stop_after_client_disconnect_sec
        out.append(DelayedRescheduleInfo(alloc.id, alloc, when))
    return out


class AllocNameIndex:
    """Tracks which alloc name indexes are in use (ref reconcile_util.go
    newAllocNameIndex + bitmapFrom)."""

    def __init__(self, job_id: str, task_group: str, count: int,
                 in_use: AllocSet):
        self.job_id = job_id
        self.task_group = task_group
        self.count = count
        self.used: set[int] = set()
        for alloc in in_use.values():
            idx = alloc_name_index(alloc.name)
            if idx >= 0:
                self.used.add(idx)

    def _name(self, idx: int) -> str:
        return alloc_name(self.job_id, self.task_group, idx)

    def highest(self, n: int) -> set[str]:
        """The n highest used names, removing them from the index."""
        out: set[str] = set()
        for idx in sorted(self.used, reverse=True):
            if len(out) >= n:
                break
            out.add(self._name(idx))
            self.used.discard(idx)
        return out

    def unset_index(self, idx: int) -> None:
        self.used.discard(idx)

    def next(self, n: int) -> list[str]:
        """Next n free names within [0, count), overflowing past count."""
        if not self.used:
            # fresh job: every index is free — mint in one comprehension
            # (a 50k-instance job calls this once with n == count)
            prefix = f"{self.job_id}.{self.task_group}["
            self.used.update(range(n))
            return [f"{prefix}{i}]" for i in range(n)]
        out: list[str] = []
        for idx in range(self.count):
            if len(out) == n:
                return out
            if idx not in self.used:
                out.append(self._name(idx))
                self.used.add(idx)
        idx = self.count
        while len(out) < n:
            if idx not in self.used:
                out.append(self._name(idx))
                self.used.add(idx)
            idx += 1
        return out

    def next_canaries(self, n: int, existing: AllocSet,
                      destructive: AllocSet) -> list[str]:
        """Canary names: prefer indexes of destructive updates, then free
        indexes, then indexes past count (ref NextCanaries)."""
        out: list[str] = []
        existing_names = name_set(existing)
        destructive_idx = sorted({alloc_name_index(a.name)
                                  for a in destructive.values()} - {-1})
        for idx in destructive_idx:
            if len(out) == n:
                return out
            nm = self._name(idx)
            if nm not in existing_names:
                out.append(nm)
                self.used.add(idx)
        for idx in range(self.count):
            if len(out) == n:
                return out
            if idx not in self.used:
                nm = self._name(idx)
                if nm not in existing_names:
                    out.append(nm)
                    self.used.add(idx)
        idx = self.count
        while len(out) < n:
            out.append(self._name(idx))
            idx += 1
        return out
