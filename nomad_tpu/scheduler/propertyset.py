"""Property sets: bookkeeping of attribute-value usage across existing and
in-plan allocations (ref scheduler/propertyset.go). Shared by
distinct_property constraints and spread scoring.
"""
from __future__ import annotations

from typing import Optional

from ..structs import Constraint, Node
from .feasible import resolve_target


class PropertySet:
    def __init__(self, ctx, job):
        self.ctx = ctx
        self.job = job
        self.namespace = job.namespace if job else "default"
        self.job_id = job.id if job else ""
        self.tg_name: Optional[str] = None
        self.constraint: Optional[Constraint] = None
        self.target_attribute: str = ""
        self.allowed_count: int = 0
        self.error: str = ""
        # existing usage computed lazily: value -> count
        self._existing: Optional[dict[str, int]] = None

    # ---- configuration (ref propertyset.go SetJobConstraint/SetTGConstraint) ----

    def set_job_constraint(self, constraint: Constraint) -> None:
        self._set_constraint(constraint, None)

    def set_tg_constraint(self, constraint: Constraint, tg_name: str) -> None:
        self._set_constraint(constraint, tg_name)

    def set_target_attribute(self, attribute: str, tg_name: Optional[str] = None
                             ) -> None:
        """Spread path: no count limit, just usage counting."""
        self.target_attribute = attribute
        self.tg_name = tg_name
        self.allowed_count = 0

    def _set_constraint(self, constraint: Constraint,
                        tg_name: Optional[str]) -> None:
        self.constraint = constraint
        self.target_attribute = constraint.ltarget
        self.tg_name = tg_name
        if constraint.rtarget:
            try:
                self.allowed_count = int(constraint.rtarget)
                if self.allowed_count < 1:
                    self.error = "distinct_property constraint value must be >= 1"
            except ValueError:
                self.error = (f"distinct_property constraint value "
                              f"{constraint.rtarget!r} is not an integer")
                self.allowed_count = 1
        else:
            self.allowed_count = 1

    # ---- usage ----

    def _existing_counts(self) -> dict[str, int]:
        if self._existing is not None:
            return self._existing
        counts: dict[str, int] = {}
        allocs = self.ctx.state.allocs_by_job(self.namespace, self.job_id)
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if self.tg_name is not None and alloc.task_group != self.tg_name:
                continue
            node = self.ctx.state.node_by_id(alloc.node_id)
            if node is None:
                continue
            val, ok = resolve_target(self.target_attribute, node)
            if ok and val is not None:
                counts[str(val)] = counts.get(str(val), 0) + 1
        self._existing = counts
        return counts

    def _plan_deltas(self) -> tuple[dict[str, int], dict[str, int]]:
        """(proposed placements per value, stopped per value) from the plan."""
        placed: dict[str, int] = {}
        stopped: dict[str, int] = {}
        plan = self.ctx.plan
        if plan is None:
            return placed, stopped
        for node_id, allocs in plan.node_allocation.items():
            node = self.ctx.state.node_by_id(node_id)
            if node is None:
                continue
            val, ok = resolve_target(self.target_attribute, node)
            if not (ok and val is not None):
                continue
            for alloc in allocs:
                if alloc.job_id != self.job_id or alloc.namespace != self.namespace:
                    continue
                if self.tg_name is not None and alloc.task_group != self.tg_name:
                    continue
                placed[str(val)] = placed.get(str(val), 0) + 1
        for node_id, allocs in list(plan.node_update.items()) + \
                list(plan.node_preemptions.items()):
            node = self.ctx.state.node_by_id(node_id)
            if node is None:
                continue
            val, ok = resolve_target(self.target_attribute, node)
            if not (ok and val is not None):
                continue
            for alloc in allocs:
                if alloc.job_id != self.job_id or alloc.namespace != self.namespace:
                    continue
                if self.tg_name is not None and alloc.task_group != self.tg_name:
                    continue
                stopped[str(val)] = stopped.get(str(val), 0) + 1
        return placed, stopped

    def used_counts(self) -> dict[str, int]:
        """Combined existing + plan usage per property value
        (ref propertyset.go UsedCounts)."""
        combined = dict(self._existing_counts())
        placed, stopped = self._plan_deltas()
        for v, n in placed.items():
            combined[v] = combined.get(v, 0) + n
        for v, n in stopped.items():
            combined[v] = max(0, combined.get(v, 0) - n)
        return combined

    # ---- verdict (ref propertyset.go SatisfiesDistinctProperties) ----

    def satisfies_distinct_properties(self, node: Node) -> tuple[bool, str]:
        if self.error:
            return False, self.error
        val, ok = resolve_target(self.target_attribute, node)
        if not ok or val is None:
            return False, f"missing property {self.target_attribute!r}"
        used = self.used_counts().get(str(val), 0)
        if used >= self.allowed_count:
            return False, (f"distinct_property: {self.target_attribute}={val} "
                           f"already used {used} times (limit {self.allowed_count})")
        return True, ""
