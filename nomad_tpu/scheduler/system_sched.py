"""System/sysbatch scheduler: one alloc of each task group on every feasible
node (ref scheduler/scheduler_system.go).
"""
from __future__ import annotations

import time
from typing import Optional

from ..structs import (
    AllocatedResources, AllocatedSharedResources, Allocation, Evaluation,
    Job, Plan, DESC_NODE_TAINTED, DESC_NOT_NEEDED,
    ALLOC_CLIENT_LOST, EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
    JOB_TYPE_SYSBATCH, alloc_name, new_id,
)
from .context import EvalContext
from .stack import SystemStack, SelectOptions
from .util import ready_nodes_in_dcs, tainted_nodes, tasks_updated

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5


class SystemScheduler:
    """ref scheduler_system.go:27"""

    def __init__(self, state, planner, sysbatch: bool = False, logger=None):
        self.state = state
        self.planner = planner
        self.sysbatch = sysbatch
        self.logger = logger
        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.failed_tg_allocs: dict[str, object] = {}
        self.queued_allocs: dict[str, int] = {}

    def process(self, eval: Evaluation) -> None:
        self.eval = eval
        attempts = 0
        while attempts < MAX_SYSTEM_SCHEDULE_ATTEMPTS:
            done = self._process()
            if done:
                ev = eval.copy()
                ev.status = EVAL_STATUS_COMPLETE
                ev.failed_tg_allocs = dict(self.failed_tg_allocs)
                ev.queued_allocations = dict(self.queued_allocs)
                self.planner.update_eval(ev)
                return
            attempts += 1
            self.state = self.planner.refresh_snapshot(self.state)
        ev = eval.copy()
        ev.status = EVAL_STATUS_FAILED
        ev.status_description = "maximum attempts reached"
        self.planner.update_eval(ev)

    def _process(self) -> bool:
        eval = self.eval
        self.job = self.state.job_by_id(eval.namespace, eval.job_id)
        self.plan = eval.make_plan(self.job)
        self.plan.snapshot_index = self.state.latest_index()
        self.ctx = EvalContext(self.state, self.plan, self.logger)
        self.stack = SystemStack(self.ctx, self.sysbatch)
        self.failed_tg_allocs = {}
        self.queued_allocs = {}

        if self.job and not self.job.stopped():
            nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
            self.ctx.metrics.nodes_available = by_dc
            self.stack.set_job(self.job)
        else:
            nodes = []

        allocs = self.state.allocs_by_job(eval.namespace, eval.job_id)
        tainted = tainted_nodes(self.state, allocs)

        # index existing allocs by (node, tg)
        existing: dict[tuple[str, str], Allocation] = {}
        for a in allocs:
            key = (a.node_id, a.task_group)
            cur = existing.get(key)
            if cur is None or cur.create_index < a.create_index:
                existing[key] = a

        node_ids = {n.id for n in nodes}
        stopped = self.job is None or self.job.stopped()

        # stop allocs on nodes that are no longer eligible / down / gone
        for (node_id, tg_name), a in existing.items():
            if a.terminal_status():
                continue
            if stopped or self.job.lookup_task_group(tg_name) is None:
                self.plan.append_stopped_alloc(a, DESC_NOT_NEEDED)
                continue
            if node_id in tainted:
                node = tainted[node_id]
                if node is None or node.terminal_status():
                    self.plan.append_stopped_alloc(
                        a, DESC_NODE_TAINTED, client_status=ALLOC_CLIENT_LOST)
                elif a.desired_transition.should_migrate():
                    # draining or ineligible but alive: only the drainer's
                    # desired_transition stops system allocs, so
                    # ignore_system_jobs is honored and toggling node
                    # eligibility doesn't kill system workloads (ref
                    # scheduler_system.go diffSystemAllocs defers every
                    # non-terminal tainted node to ShouldMigrate)
                    self.plan.append_stopped_alloc(a, DESC_NODE_TAINTED)
                continue
            if node_id not in node_ids:
                # e.g. datacenter no longer matches
                self.plan.append_stopped_alloc(a, DESC_NOT_NEEDED)

        # place on nodes that lack a live (or, sysbatch, successful) alloc
        if not stopped:
            for tg in self.job.task_groups:
                self.queued_allocs.setdefault(tg.name, 0)
                for node in nodes:
                    a = existing.get((node.id, tg.name))
                    stopped_for_update = None
                    if a is not None:
                        if not a.terminal_status():
                            # update in place / destructive if job changed
                            if a.job is not None and \
                               a.job.version != self.job.version and \
                               tasks_updated(a.job, self.job, tg.name):
                                self.plan.append_stopped_alloc(
                                    a, "alloc is being updated due to job update")
                                stopped_for_update = a
                            else:
                                continue
                        elif self.sysbatch and a.ran_successfully():
                            continue  # sysbatch: done is done
                        elif self.sysbatch and a.terminal_status() and \
                                a.job is not None and \
                                a.job.version == self.job.version:
                            continue  # don't rerun failed sysbatch on same version
                        elif not self.sysbatch and a.server_terminal_status():
                            continue
                    if not self._place_on_node(tg, node):
                        if stopped_for_update is not None:
                            # keep the healthy old version running rather than
                            # stopping it with no replacement
                            self.plan.pop_update(stopped_for_update)
                        self.queued_allocs[tg.name] += 1

        if self.plan.is_no_op():
            return True
        result = self.planner.submit_plan(self.plan)
        if result is None:
            return False
        full, _, _ = result.full_commit(self.plan)
        return full

    def _place_on_node(self, tg, node) -> bool:
        self.stack.set_nodes([node])
        name = alloc_name(self.job.id, tg.name, 0)
        option = self.stack.select(tg, SelectOptions(alloc_name=name))
        if option is None:
            # preemption retry for system jobs
            cfg = self.ctx.scheduler_config.preemption_config
            enabled = (cfg.sysbatch_scheduler_enabled if self.sysbatch
                       else cfg.system_scheduler_enabled)
            if enabled:
                option = self.stack.select(
                    tg, SelectOptions(alloc_name=name, preempt=True))
            if option is None:
                self.failed_tg_allocs[tg.name] = self.ctx.metrics.copy()
                return False
        if option.preempted_allocs:
            for victim in option.preempted_allocs:
                self.plan.append_preempted_alloc(victim, self.eval.id)
        resources = AllocatedResources(
            tasks=dict(option.task_resources),
            shared=option.alloc_resources or AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb))
        alloc = Allocation(
            id=new_id(),
            namespace=self.eval.namespace,
            eval_id=self.eval.id,
            name=name,
            job_id=self.eval.job_id,
            task_group=tg.name,
            metrics=self.ctx.metrics.copy(),
            node_id=option.node.id,
            node_name=option.node.name,
            allocated_resources=resources,
            desired_status="run",
            client_status="pending",
        )
        self.plan.append_alloc(alloc, None)
        return True
