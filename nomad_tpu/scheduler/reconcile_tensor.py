"""Tensorized reconciler diff (ISSUE 15): the alloc-name slot algebra of
the reconciler — desired-vs-existing set membership, slot counts, and the
stop/place index deltas — as fixed-shape masked numpy tensors instead of
per-alloc python set walks.

The reconciler's hot inner diff is name-slot arithmetic: which of the
task group's `count` desired indices are held by live allocs, which are
free for fresh placements, and which highest-indexed holders must stop
on a scale-down. `AllocNameIndex` modeled that as a python `set[int]`
walked per slot; `TensorNameIndex` below is its FIELD-EXACT twin backed
by a bool membership mask over the pow2-padded desired axis (the same
bucketing discipline the solver's node axis rides, so the mask shapes
are enumerable) plus a small host-side overflow set for indices past the
pad — the unbounded tail the reference's `next()` can mint on scale
races. Selection (`next`, `highest`, `next_canaries`) lowers to
flatnonzero/slice over the mask; the overflow tail and every irregular
policy edge (canaries, disconnects, duplicate-name cleanup) stay
host-side, exactly as ISSUE 15 scopes them.

Equality contract: every public behavior — returned name lists AND the
mutation of the membership state — matches `AllocNameIndex` exactly on
arbitrary inputs; tests/test_fused.py fuzzes the pair op-for-op and
pins full-reconciler field-exactness with the twin on vs off.

NOMAD_RECONCILE_TENSOR=0 disables the twin (the fuzz differential's
oracle switch and the ops escape hatch); `make_name_index` is the one
construction seam the reconciler uses.
"""
from __future__ import annotations

import os

import numpy as np

from ..solver.buckets import pow2
from ..structs import alloc_name, alloc_name_index


def enabled() -> bool:
    return os.environ.get("NOMAD_RECONCILE_TENSOR", "") != "0"


def name_index_array(in_use) -> np.ndarray:
    """Parse every alloc's name-slot index into one i64 vector (the
    membership lowering; -1 = unparseable name, never a member)."""
    if not in_use:
        return np.empty(0, np.int64)
    return np.fromiter((alloc_name_index(a.name) for a in in_use.values()),
                       np.int64, count=len(in_use))


class TensorNameIndex:
    """`AllocNameIndex`'s fixed-shape masked twin: slot membership as a
    bool[P] mask (P = pow2(count)), slot selection as vectorized mask
    ops. Same constructor and method surface; same returned names; same
    membership mutations."""

    __slots__ = ("job_id", "task_group", "count", "_p", "mask",
                 "_overflow")

    def __init__(self, job_id: str, task_group: str, count: int, in_use):
        self.job_id = job_id
        self.task_group = task_group
        self.count = count
        self._p = pow2(max(int(count), 1))
        self.mask = np.zeros(self._p, bool)
        self._overflow: set[int] = set()
        idxs = name_index_array(in_use)
        idxs = idxs[idxs >= 0]
        if len(idxs):
            in_pad = idxs[idxs < self._p]
            self.mask[in_pad] = True
            for i in idxs[idxs >= self._p].tolist():
                self._overflow.add(int(i))

    # ------------------------------------------------------ compatibility

    @property
    def used(self) -> set[int]:
        """The reference's `set[int]` view (read-only materialization —
        mutation goes through the methods below)."""
        return set(np.flatnonzero(self.mask).tolist()) | self._overflow

    def _name(self, idx: int) -> str:
        return alloc_name(self.job_id, self.task_group, idx)

    def _empty(self) -> bool:
        return not self._overflow and not self.mask.any()

    def _has(self, idx: int) -> bool:
        return (self.mask[idx] if idx < self._p
                else idx in self._overflow)

    def _add(self, idx: int) -> None:
        if idx < self._p:
            self.mask[idx] = True
        else:
            self._overflow.add(idx)

    # ------------------------------------------------------------ the API

    def highest(self, n: int) -> set[str]:
        """The n highest used names, removing them from the index —
        overflow indices (all >= P) first, then the mask tail."""
        out: set[str] = set()
        for idx in sorted(self._overflow, reverse=True):
            if len(out) >= n:
                return out
            out.add(self._name(idx))
            self._overflow.discard(idx)
        held = np.flatnonzero(self.mask)
        take = held[::-1][:n - len(out)]
        for idx in take.tolist():
            out.add(self._name(int(idx)))
        self.mask[take] = False
        return out

    def unset_index(self, idx: int) -> None:
        if idx < self._p:
            if idx >= 0:
                self.mask[idx] = False
        else:
            self._overflow.discard(idx)

    def next(self, n: int) -> list[str]:
        """Next n free names within [0, count), overflowing past count."""
        if self._empty():
            # fresh job: every index is free — one vector mint
            prefix = f"{self.job_id}.{self.task_group}["
            if n <= self._p:
                self.mask[:n] = True
            else:
                self.mask[:] = True
                self._overflow.update(range(self._p, n))
            return [f"{prefix}{i}]" for i in range(n)]
        free = np.flatnonzero(~self.mask[:self.count])
        take = free[:n]
        out = [self._name(int(i)) for i in take.tolist()]
        self.mask[take] = True
        idx = self.count
        while len(out) < n:
            if not self._has(idx):
                out.append(self._name(idx))
                self._add(idx)
            idx += 1
        return out

    def next_canaries(self, n: int, existing, destructive) -> list[str]:
        """Canary names: prefer indexes of destructive updates, then free
        indexes, then indexes past count (ref NextCanaries)."""
        out: list[str] = []
        existing_names = {a.name for a in existing.values()}
        d_idx = name_index_array(destructive)
        for idx in np.unique(d_idx[d_idx >= 0]).tolist():
            if len(out) == n:
                return out
            nm = self._name(int(idx))
            if nm not in existing_names:
                out.append(nm)
                self._add(int(idx))
        free = np.flatnonzero(~self.mask[:self.count])
        for idx in free.tolist():
            if len(out) == n:
                return out
            nm = self._name(int(idx))
            if nm not in existing_names:
                out.append(nm)
                self.mask[idx] = True
        idx = self.count
        while len(out) < n:
            out.append(self._name(idx))
            idx += 1
        return out


def make_name_index(job_id: str, task_group: str, count: int, in_use):
    """The reconciler's one construction seam: the masked tensor twin by
    default, the reference python-set index under
    NOMAD_RECONCILE_TENSOR=0 (the differential oracle)."""
    from .reconcile_util import AllocNameIndex
    if enabled():
        return TensorNameIndex(job_id, task_group, count, in_use)
    return AllocNameIndex(job_id, task_group, count, in_use)
