"""Scheduler test harness (ref scheduler/testing.go): real state store +
fake Planner capturing plans and applying them to state — the entire
scheduler is exercised as a pure function of (state, eval) -> plan.
"""
from __future__ import annotations

from typing import Optional

from ..state import StateStore
from ..structs import (
    Allocation, Evaluation, Plan, PlanResult, ALLOC_DESIRED_STOP,
)


class _PlanApplyRequest:
    """Shape consumed by StateStore.upsert_plan_results (the
    ApplyPlanResultsRequest analog)."""

    def __init__(self, plan: Plan):
        self.alloc_updates = [a for allocs in plan.node_update.values()
                              for a in allocs]
        self.alloc_placements = [a for allocs in plan.node_allocation.values()
                                 for a in allocs]
        self.alloc_preemptions = [a for allocs in plan.node_preemptions.values()
                                  for a in allocs]
        self.deployment = plan.deployment
        self.deployment_updates = plan.deployment_updates
        self.eval_id = plan.eval_id


class Harness:
    """ref testing.go:43"""

    def __init__(self, state: Optional[StateStore] = None):
        self.state = state or StateStore()
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []
        self.created_evals: list[Evaluation] = []
        self.reblocked_evals: list[Evaluation] = []
        self.next_index = 1
        self.reject_plan = False

    def get_next_index(self) -> int:
        idx = self.next_index
        self.next_index += 1
        return idx

    # ---- Planner interface ----

    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        self.plans.append(plan)
        if self.reject_plan:
            return PlanResult()
        index = self.get_next_index()
        req = _PlanApplyRequest(plan)
        self.state.upsert_plan_results(index, req)
        return PlanResult(
            node_update=dict(plan.node_update),
            node_allocation=dict(plan.node_allocation),
            node_preemptions=dict(plan.node_preemptions),
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=index)

    def update_eval(self, eval: Evaluation) -> None:
        self.evals.append(eval)
        # mirror production: the worker persists eval status via Raft
        self.state.upsert_evals(self.get_next_index(), [eval])

    def create_eval(self, eval: Evaluation) -> None:
        self.created_evals.append(eval)

    def reblock_eval(self, eval: Evaluation) -> None:
        self.reblocked_evals.append(eval)

    def refresh_snapshot(self, old_snap):
        return self.state.snapshot()

    # ---- driving ----

    def process(self, scheduler_factory, eval: Evaluation) -> None:
        """Snapshot state and run the scheduler (ref testing.go:270)."""
        snap = self.state.snapshot()
        sched = scheduler_factory(snap, self)
        sched.process(eval)
