"""Selection iterators (ref scheduler/select.go): bounded lookahead + max.
"""
from __future__ import annotations

from typing import Optional

from .rank import RankedNode, RankIterator

# ref scheduler/stack.go:10-18
SKIP_SCORE_THRESHOLD = 0.0
MAX_SKIP = 3


class LimitIterator(RankIterator):
    """Yield at most `limit` options, skipping up to MAX_SKIP low-scoring ones
    (ref select.go LimitIterator)."""

    def __init__(self, ctx, source: RankIterator, limit: int,
                 skip_threshold: float = SKIP_SCORE_THRESHOLD,
                 max_skip: int = MAX_SKIP):
        self.ctx = ctx
        self.source = source
        self.limit = limit
        self.skip_threshold = skip_threshold
        self.max_skip = max_skip
        self.scan_limit_reached = False
        self.seen = 0
        self.skipped: list[RankedNode] = []

    def set_limit(self, limit: int) -> None:
        self.limit = limit

    def next(self) -> Optional[RankedNode]:
        if self.seen == self.limit:
            return None
        option = self.source.next()
        if option is None:
            return self._next_from_skipped()
        if not self.scan_limit_reached and \
           option.final_score <= self.skip_threshold and \
           len(self.skipped) < self.max_skip:
            self.skipped.append(option)
            if len(self.skipped) == self.max_skip:
                self.scan_limit_reached = True
            return self.next()
        self.seen += 1
        return option

    def _next_from_skipped(self) -> Optional[RankedNode]:
        if self.skipped:
            option = self.skipped.pop(0)
            self.seen += 1
            return option
        return None

    def reset(self) -> None:
        self.source.reset()
        self.seen = 0
        self.skipped = []
        self.scan_limit_reached = False


class MaxScoreIterator(RankIterator):
    """Consume the source and return only the best option (ref select.go)."""

    def __init__(self, ctx, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.done = False

    def next(self) -> Optional[RankedNode]:
        if self.done:
            return None
        best: Optional[RankedNode] = None
        while True:
            option = self.source.next()
            if option is None:
                break
            if best is None or option.final_score > best.final_score:
                best = option
        self.done = True
        return best

    def reset(self) -> None:
        self.source.reset()
        self.done = False
