"""Controllable time source (ISSUE 6).

The multi-server raft/operator tests were sleep-and-hope: election
deadlines, heartbeat TTLs, and autopilot thresholds all read the wall
clock directly, so the only way to exercise "a node misses its TTL" or
"the leader goes quiet past the election timeout" was to actually wait —
and under a loaded CI box the waits raced the GIL. A `Clock` abstraction
makes every time-dependent decision injectable:

  * `Clock` — the real thing (`monotonic`/`time`/`sleep`), the default
    everywhere; production code pays one attribute indirection.
  * `ManualClock` — virtual time advanced explicitly by `advance()` /
    `set_time()`. `sleep()` blocks until virtual time passes (woken by
    `advance`), so a component's timers fire exactly when the test says
    so and never otherwise.

Only DECISIONS ride the clock (deadline comparisons, TTL arithmetic);
thread poll cadences stay real — a raft election loop under a
ManualClock still polls every few real milliseconds, but campaigns only
once the test advances virtual time past the (seeded) deadline. That
split keeps the change surface small while making timer behavior
deterministic. See docs/FAILOVER.md.
"""
from __future__ import annotations

import threading
import time


class Clock:
    """Real time. `monotonic()` feeds interval math (raft deadlines),
    `time()` feeds wall-clock timestamps (heartbeat TTL deadlines)."""

    def monotonic(self) -> float:
        return time.monotonic()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class ManualClock(Clock):
    """Virtual time under test control. Starts at an arbitrary epoch so
    code that assumes time() > 0 keeps working; monotonic() and time()
    advance in lockstep (tests reason about ONE timeline)."""

    def __init__(self, start: float = 1_000_000.0):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._now = float(start)

    def monotonic(self) -> float:
        with self._lock:
            return self._now

    def time(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        """Block until virtual time has advanced past now+seconds. A
        zero/negative sleep yields the thread (like time.sleep(0))."""
        if seconds <= 0:
            time.sleep(0)
            return
        with self._lock:
            deadline = self._now + seconds
            while self._now < deadline:
                self._cond.wait(0.05)

    def advance(self, seconds: float) -> float:
        with self._lock:
            self._now += float(seconds)
            self._cond.notify_all()
            return self._now

    def set_time(self, now: float) -> None:
        with self._lock:
            if now < self._now:
                raise ValueError("ManualClock cannot run backwards")
            self._now = float(now)
            self._cond.notify_all()


# the process default; components take `clock=None` -> REAL
REAL = Clock()
