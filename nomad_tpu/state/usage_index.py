"""Dense per-node resource matrices, maintained incrementally by the state
store — the tensor twin of the allocs table.

Round-1 profiling showed the TPU solve itself is milliseconds while
`build_group_tensors` burned seconds re-deriving [N, R'] capacity/usage
matrices from Python objects on every evaluation (a loop over all nodes
calling `proposed_allocs` per node — VERDICT r1 weak #1). This index keeps
those matrices up to date on every state commit, so an eval's solver input
is two O(N·R') array copies plus a sparse in-plan correction instead of an
O(allocs) object walk.

The extended resource axis R' (XR_*) packs the scalar dims (cpu, mem, disk)
with the coarse sequential-resource dims (free dynamic ports, bandwidth) —
one masked floor-divide on device yields per-node instance capacity
(ref nomad/structs/funcs.go:147 AllocsFit, the scalar original).

Versioning contract (ISSUE 4, docs/DEVICE_STATE_CACHE.md): every usage
mutation bumps `version` and mirrors its signed (row, delta, count_delta)
records into an append-only `DeltaLog` — the EXACT stream `_flush` feeds
`np.add.at`, so any consumer that replays the log from a matching start
state reproduces `used` bit-identically. Node-set / capacity-row changes
bump `epoch` instead (no delta form — consumers rebuild). The solver's
device-resident tensor cache (nomad_tpu/solver/state_cache.py) is the one
consumer; `UsageView` carries (uid, epoch, version, delta_log) so a
snapshot is enough to key the cache.

Taint mask (ISSUE 10, docs/NODE_FAILURE.md): node status/eligibility/
drain changes ride the SAME journal as an eligibility-mask column
(`elig`, f32[N], 1.0 = schedulable) instead of bumping `epoch` — a
5-tuple journal entry `(version, row, None, 0, elig)` is a taint SET,
distinguishable from a usage delta by its None delta. A mass node
failure (10% of the fleet at once) therefore advances consumers through
ordinary replay: cap/used tensors and per-shard device twins stay
resident, `nomad.solver.state_cache.reseeds` stays flat. `epoch` is
reserved for true node-set mutation: add, remove (drop_node), or a
capacity-row change.
"""
from __future__ import annotations

import itertools
from typing import Optional

import numpy as np

_UID = itertools.count(1)


class DeltaLog:
    """Append-only journal of usage deltas, one entry per `_pending`
    append: (version, row, usage_delta_tuple, count_delta) — plus taint
    entries (version, row, None, 0, elig) that SET the eligibility-mask
    column (ISSUE 10; consumers key on the None delta). Writers hold
    the owning store's lock. `tail` is an immutable (floor_seq, entries)
    pair swapped atomically on trim, so lock-free readers grab one
    consistent generation: entries[k] is absolute sequence floor_seq + k,
    and a reader that cached an older list only misses entries NEWER than
    its target version. KEEP bounds memory; a consumer whose applied
    sequence predates `floor_seq` sees a gap and must rebuild."""

    MAX = 262_144
    KEEP = 131_072

    __slots__ = ("tail",)

    def __init__(self):
        self.tail: tuple[int, list] = (0, [])

    def append(self, entry: tuple) -> None:
        floor, entries = self.tail
        entries.append(entry)
        if len(entries) > self.MAX:
            drop = len(entries) - self.KEEP
            self.tail = (floor + drop, entries[drop:])

    def head_seq(self) -> int:
        floor, entries = self.tail
        return floor + len(entries)

# extended resource axis layout (solver kernels + tensorize must match)
XR_CPU, XR_MEM, XR_DISK, XR_PORTS, XR_MBITS = 0, 1, 2, 3, 4
NUM_XR = 5

# single-sourced from structs so XR_PORTS agrees with real port assignment
# (ref nomad/structs/network.go DefaultMinDynamicPort/DefaultMaxDynamicPort)
from ..structs.network import (     # noqa: E402
    DEFAULT_MAX_DYNAMIC_PORT, DEFAULT_MIN_DYNAMIC_PORT,
)

DYN_PORT_SPAN = DEFAULT_MAX_DYNAMIC_PORT - DEFAULT_MIN_DYNAMIC_PORT + 1


def node_capacity_tuple(node) -> tuple:
    """Usable capacity (total − node reservation) in XR layout."""
    res, rsv = node.node_resources, node.reserved_resources
    mbits = 0
    for n in res.networks:
        mbits += n.mbits
    return (float(max(0, res.cpu.cpu_shares - rsv.cpu_shares)),
            float(max(0, res.memory.memory_mb - rsv.memory_mb)),
            float(max(0, res.disk.disk_mb - rsv.disk_mb)),
            float(DYN_PORT_SPAN),
            float(mbits))


def _resources_usage_tuple(res) -> tuple:
    """XR usage of one AllocatedResources. Cached on the (immutable by
    convention) resources object: allocs stamped out from one task group
    share the object, so a 50k-alloc job computes this once."""
    cached = getattr(res, "_xr_usage", None)
    if cached is not None:
        return cached
    cpu = 0.0
    mem = 0.0
    ports = 0.0
    mbits = 0.0
    for net in res.shared.networks:
        mbits += net.mbits
        ports += len(net.dynamic_ports)
        for p in net.reserved_ports:
            if DEFAULT_MIN_DYNAMIC_PORT <= p.value <= DEFAULT_MAX_DYNAMIC_PORT:
                ports += 1
    for tr in res.tasks.values():
        cpu += tr.cpu_shares
        mem += (tr.memory_max_mb if tr.memory_max_mb > tr.memory_mb
                else tr.memory_mb)
        for net in tr.networks:
            mbits += net.mbits
            ports += len(net.dynamic_ports)
            for p in net.reserved_ports:
                if DEFAULT_MIN_DYNAMIC_PORT <= p.value \
                        <= DEFAULT_MAX_DYNAMIC_PORT:
                    ports += 1
    row = (cpu, mem, float(res.shared.disk_mb), ports, mbits)
    try:
        res._xr_usage = row
    except AttributeError:
        pass          # slotted/frozen object: just skip the cache
    return row


def alloc_usage_tuple(alloc) -> tuple:
    return _resources_usage_tuple(alloc.allocated_resources)


def resources_sequential(res) -> bool:
    """Does this resource set claim per-node sequential resources (ports,
    cores, devices)? Nodes where every alloc is non-sequential can be
    plan-checked with one dense vector compare; anything sequential takes
    the exact NetworkIndex/core-overlap path (allocs_fit)."""
    cached = getattr(res, "_xr_seq", None)
    if cached is not None:
        return cached
    seq = bool(res.shared.networks) or bool(res.shared.ports)
    if not seq:
        for tr in res.tasks.values():
            if tr.networks or tr.devices or tr.reserved_cores:
                seq = True
                break
    try:
        res._xr_seq = seq
    except AttributeError:
        pass
    return seq


class UsageIndex:
    """cap/used [N, R'] matrices + node-id row map, updated on every node
    and alloc write. Writers must hold the owning store's lock."""

    _GROW = 256

    def __init__(self):
        self.row: dict[str, int] = {}            # node_id -> row
        self.node_ids: list[str] = []            # row -> node_id
        self.cap = np.zeros((0, NUM_XR), np.float32)
        self.used = np.zeros((0, NUM_XR), np.float32)
        # live (non-terminal) alloc count per row — the per-node density
        # vector the tensor cache advances alongside used
        self.counts = np.zeros(0, np.int32)
        # eligibility mask column (ISSUE 10): 1.0 = node schedulable
        # (ready + eligible + not draining). Status flips journal a
        # taint SET entry — no epoch bump — so tensor-cache consumers
        # survive a mass node failure without reseeding.
        self.elig = np.ones(0, np.float32)
        # node-class id column (ISSUE 11): -1 = classless; ids index
        # `class_names`, a grow-only universe bounded by distinct
        # operator-assigned classes. Host-side only (never journaled —
        # no device twin reads it): the explain path's per-class
        # histograms gather `class_col[rows]` vectorized instead of a
        # GIL-serializing python walk over 10k node objects per eval.
        self.class_col = np.full(0, -1, np.int32)
        self.class_names: list[str] = []
        self._class_lookup: dict[str, int] = {}
        self._n = 0                              # live rows
        # alloc_id -> (row, usage tuple, sequential?) for exact removal
        self._contrib: dict[str, tuple[int, tuple, bool]] = {}
        # rows with >= 1 sequential-resource alloc (ports/cores/devices):
        # those nodes need the exact allocs_fit plan check
        self.seq_rows: dict[int, int] = {}
        # deferred signed (row, delta) updates: a 50k-alloc plan apply makes
        # one np.add.at instead of 50k per-row adds; flushed before any read
        self._pending: list[tuple[int, tuple]] = []
        # versioning contract (module docstring): uid identifies this
        # index instance (rebuild/restore mints a new one), epoch
        # fingerprints the node set + capacity rows, version counts
        # usage mutations; delta_log mirrors every _pending append
        self.uid = next(_UID)
        self.epoch = 0
        self.version = 0
        self.delta_log = DeltaLog()
        self._view_cache: Optional[tuple] = None

    # ------------------------------------------------------------- writers

    def _flush(self) -> None:
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        rows = np.fromiter((p[0] for p in pending), np.int64,
                           count=len(pending))
        deltas = np.array([p[1] for p in pending], np.float32)
        np.add.at(self.used, rows, deltas)

    def _ensure_capacity(self, n: int) -> None:
        if n <= self.cap.shape[0]:
            return
        self._flush()
        grow = max(n, self.cap.shape[0] + self._GROW,
                   self.cap.shape[0] * 2)
        cap = np.zeros((grow, NUM_XR), np.float32)
        used = np.zeros((grow, NUM_XR), np.float32)
        counts = np.zeros(grow, np.int32)
        elig = np.ones(grow, np.float32)
        class_col = np.full(grow, -1, np.int32)
        cap[:self._n] = self.cap[:self._n]
        used[:self._n] = self.used[:self._n]
        counts[:self._n] = self.counts[:self._n]
        elig[:self._n] = self.elig[:self._n]
        class_col[:self._n] = self.class_col[:self._n]
        self.cap, self.used, self.counts, self.elig = cap, used, counts, elig
        self.class_col = class_col

    def set_node(self, node) -> None:
        self.version += 1
        r = self.row.get(node.id)
        cap_row = np.asarray(node_capacity_tuple(node), np.float32)
        ready = getattr(node, "ready", None)
        elig = 1.0 if (ready is None or ready()) else 0.0
        if r is None:
            r = self._n
            self._ensure_capacity(r + 1)
            self.row[node.id] = r
            self.node_ids.append(node.id)
            self._n += 1
            self.epoch += 1             # node-set fingerprint changed
            self.elig[r] = elig         # epoch miss: consumers reseed
        elif not np.array_equal(self.cap[r], cap_row):
            self.epoch += 1             # capacity row changed in place
            self.elig[r] = elig
        elif self.elig[r] != elig:
            # re-register flipping schedulability (a down node coming
            # back): journal the taint SET so consumers advance in place
            self.elig[r] = elig
            self.delta_log.append((self.version, r, None, 0, elig))
        self.cap[r] = cap_row
        klass = getattr(node, "node_class", "") or ""
        if not klass:
            self.class_col[r] = -1
        else:
            cid = self._class_lookup.get(klass)
            if cid is None:
                cid = self._class_lookup[klass] = len(self.class_names)
                self.class_names.append(klass)
            self.class_col[r] = cid

    def set_node_taint(self, node_id: str, eligible: bool) -> None:
        """Journal a schedulability flip for an existing node (status/
        eligibility/drain change) WITHOUT touching `epoch` — the taint
        rides the delta log, so resident tensor-cache twins advance
        through a mass failure instead of reseeding (ISSUE 10)."""
        r = self.row.get(node_id)
        if r is None:
            return
        val = 1.0 if eligible else 0.0
        if self.elig[r] == val:
            return                      # no-op flips don't pollute the log
        self.version += 1
        self.elig[r] = val
        self.delta_log.append((self.version, r, None, 0, val))

    def drop_node(self, node_id: str) -> None:
        """Zero the row but keep the slot: rows are append-only so snapshot
        row maps stay valid; dead slots are rare (node GC) and harmless."""
        r = self.row.pop(node_id, None)
        if r is not None:
            self.version += 1
            self.epoch += 1             # node-set fingerprint changed
            self._flush()
            self.cap[r] = 0.0
            self.used[r] = 0.0
            self.counts[r] = 0
            self.elig[r] = 0.0          # epoch bumped: no journal entry
            self.class_col[r] = -1
            # orphan the row's alloc contributions so later transitions
            # don't subtract from a zeroed row
            self._contrib = {aid: c for aid, c in self._contrib.items()
                             if c[0] != r}
            self.seq_rows.pop(r, None)

    def _retire(self, old: tuple) -> None:
        delta = tuple(-x for x in old[1])
        self._pending.append((old[0], delta))
        self.delta_log.append((self.version, old[0], delta, -1))
        self.counts[old[0]] -= 1
        if old[2]:
            left = self.seq_rows.get(old[0], 1) - 1
            if left <= 0:
                self.seq_rows.pop(old[0], None)
            else:
                self.seq_rows[old[0]] = left

    def set_alloc(self, alloc) -> None:
        self.version += 1
        old = self._contrib.pop(alloc.id, None)
        if old is not None:
            self._retire(old)
        if alloc.terminal_status():
            return
        r = self.row.get(alloc.node_id)
        if r is None:
            return                      # alloc on an unknown/removed node
        u = alloc_usage_tuple(alloc)
        seq = resources_sequential(alloc.allocated_resources)
        self._pending.append((r, u))
        self.delta_log.append((self.version, r, u, 1))
        self.counts[r] += 1
        self._contrib[alloc.id] = (r, u, seq)
        if seq:
            self.seq_rows[r] = self.seq_rows.get(r, 0) + 1

    def add_fresh_batch(self, allocs) -> None:
        """set_alloc for a batch of FRESH placements: no prior
        contribution to retire, known non-terminal (the store's fast
        path checked client_status). A 50k-alloc plan shares a handful
        of resources objects, so u/seq resolve through their on-object
        caches; the loop body is just dict stores (VERDICT r4 #5 —
        this was the largest host phase)."""
        self.version += 1
        version = self.version
        row = self.row
        pend = self._pending
        log = self.delta_log
        counts = self.counts
        contrib = self._contrib
        seq_rows = self.seq_rows
        for alloc in allocs:
            res = alloc.allocated_resources
            u = getattr(res, "_xr_usage", None)
            if u is None:
                u = _resources_usage_tuple(res)
            seq = getattr(res, "_xr_seq", None)
            if seq is None:
                seq = resources_sequential(res)
            r = row.get(alloc.node_id)
            if r is None:
                continue            # alloc on an unknown/removed node
            pend.append((r, u))
            log.append((version, r, u, 1))
            counts[r] += 1
            contrib[alloc.id] = (r, u, seq)
            if seq:
                seq_rows[r] = seq_rows.get(r, 0) + 1

    def drop_alloc(self, alloc_id: str) -> None:
        old = self._contrib.pop(alloc_id, None)
        if old is not None:
            self.version += 1
            self._retire(old)

    # ------------------------------------------------------------- readers

    def view(self) -> "UsageView":
        """Point-in-time copy for snapshots/forks, memoized by
        (version, epoch): stores that only saw non-usage writes since the
        last snapshot share one immutable-by-convention view instead of
        re-copying the matrices per snapshot."""
        self._flush()
        vc = self._view_cache
        if vc is not None and vc[0] == (self.version, self.epoch):
            return vc[1]
        v = UsageView(dict(self.row), self.cap[:self._n].copy(),
                      self.used[:self._n].copy(), dict(self.seq_rows),
                      counts=self.counts[:self._n].copy(),
                      uid=self.uid, epoch=self.epoch, version=self.version,
                      delta_log=self.delta_log,
                      elig=self.elig[:self._n].copy(),
                      class_col=self.class_col[:self._n].copy(),
                      class_names=tuple(self.class_names))
        self._view_cache = ((self.version, self.epoch), v)
        return v

    def copy(self) -> "UsageIndex":
        """Fork copy (Job.Plan dry-runs). uid=0 marks the fork
        NON-AUTHORITATIVE: its views bypass the tensor cache entirely
        (state_cache treats uid 0 like an unversioned test fake), so a
        dry-run scheduler pass can never evict the live leader stream's
        device-resident state with its own divergent mutations."""
        self._flush()
        out = UsageIndex()
        out.uid = 0
        out.row = dict(self.row)
        out.node_ids = list(self.node_ids)
        out.cap = self.cap.copy()
        out.used = self.used.copy()
        out.counts = self.counts.copy()
        out.elig = self.elig.copy()
        out.class_col = self.class_col.copy()
        out.class_names = list(self.class_names)
        out._class_lookup = dict(self._class_lookup)
        out._n = self._n
        out._contrib = dict(self._contrib)
        out.seq_rows = dict(self.seq_rows)
        return out

    def rebuild(self, nodes, allocs) -> None:
        """Full recompute (snapshot restore path). __init__ mints a new
        uid, so tensor-cache consumers keyed on the old uid miss and
        reseed — a restore is a new delta stream by definition."""
        self.__init__()
        for node in nodes:
            self.set_node(node)
        for alloc in allocs:
            self.set_alloc(alloc)

    def contribution(self, alloc_id: str) -> Optional[tuple]:
        c = self._contrib.get(alloc_id)
        return c[1] if c is not None else None


class UsageView:
    """Read-only point-in-time matrices handed to snapshots. The
    (uid, epoch, version, delta_log) stamp keys the solver's tensor cache
    (state_cache.py); plain test fakes construct views without it (uid=0
    means "no versioning — cache stays out of the way")."""

    __slots__ = ("row", "cap", "used", "seq_rows", "counts",
                 "uid", "epoch", "version", "delta_log", "elig",
                 "class_col", "class_names")

    def __init__(self, row: dict[str, int], cap: np.ndarray,
                 used: np.ndarray, seq_rows: Optional[dict[int, int]] = None,
                 counts: Optional[np.ndarray] = None, uid: int = 0,
                 epoch: int = 0, version: int = 0, delta_log=None,
                 elig: Optional[np.ndarray] = None,
                 class_col: Optional[np.ndarray] = None,
                 class_names: tuple = ()):
        self.row = row
        self.cap = cap
        self.used = used
        self.seq_rows = seq_rows or {}
        self.counts = counts
        self.uid = uid
        self.epoch = epoch
        self.version = version
        self.delta_log = delta_log
        # eligibility mask column (ISSUE 10); None on plain test fakes —
        # consumers treat a missing column as all-schedulable
        self.elig = elig
        # node-class id column + universe (ISSUE 11); None on fakes —
        # the explain path then falls back to the per-node object walk
        self.class_col = class_col
        self.class_names = class_names
