"""Replicated-state layer (ref nomad/state/): the in-memory MVCC store the
FSM applies to and schedulers snapshot from."""
from .store import StateStore, StateSnapshot  # noqa: F401
