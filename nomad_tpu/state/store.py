"""In-memory MVCC state store (ref nomad/state/state_store.go, schema.go).

Design: every stored object is treated as immutable once inserted — writers
insert fresh copies stamped with a monotonically increasing raft-style index,
so a snapshot is just a shallow copy of the table dicts taken under the write
lock. That gives the two correctness properties the scheduler hinges on
(SURVEY.md §7.2):

  * `snapshot()` — a point-in-time, never-changing view (memdb MVCC analog);
  * `snapshot_min_index(i)` — block until the store has applied index >= i,
    then snapshot (ref nomad/worker.go:536, plan_apply.go:184).

Blocking queries are built on one condition variable broadcast per commit
(watch-set analog of go-memdb). Secondary indexes (allocs by node/job/eval,
evals by job) are plain dicts maintained transactionally with the write lock.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Callable, Iterable, Optional

from .usage_index import UsageIndex

from ..metrics import record_swallowed_error
from ..structs import (
    Allocation, Deployment, Evaluation, Job, Node, SchedulerConfiguration,
    ALLOC_CLIENT_LOST, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_RUNNING,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_PENDING, ALLOC_CLIENT_UNKNOWN,
    ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT,
    EVAL_STATUS_BLOCKED, JOB_STATUS_DEAD, JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING, JOB_TYPE_SERVICE, JOB_TYPE_SYSTEM, JOB_TYPE_SYSBATCH,
    NODE_STATUS_DOWN,
)
from ..structs.summary import JobSummary, TaskGroupSummary

# replicated dedup-ack LRU bound (ISSUE 18): sized to out-live any
# client's retry window at chaos write rates while keeping the snapshot
# blob contribution trivial (token + int per entry)
RPC_DEDUP_CAP = 4096


class StateStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._index = 0                       # latest applied index
        self._table_index: dict[str, int] = {}

        # primary tables: key -> object
        self.nodes: dict[str, Node] = {}
        self.jobs: dict[tuple[str, str], Job] = {}            # (ns, id)
        self.job_versions: dict[tuple[str, str, int], Job] = {}
        self.job_summaries: dict[tuple[str, str], JobSummary] = {}
        self.evals: dict[str, Evaluation] = {}
        self.allocs: dict[str, Allocation] = {}
        self.deployments: dict[str, Deployment] = {}
        self.periodic_launches: dict[tuple[str, str], dict] = {}
        self.scheduler_config: SchedulerConfiguration = SchedulerConfiguration()
        self.namespaces: dict[str, dict] = {"default": {"name": "default"}}
        self.acl_policies: dict[str, object] = {}          # name -> ACLPolicy
        self.acl_tokens: dict[str, object] = {}            # accessor -> token
        self._acl_token_by_secret: dict[str, str] = {}     # secret -> accessor
        # scaling (ref nomad/state/schema.go scaling_policy/scaling_event)
        self.scaling_policies: dict[str, object] = {}      # id -> policy
        self._scaling_policy_by_target: dict[tuple, str] = {}
        self.scaling_events: dict[tuple[str, str], dict[str, list]] = {}
        # CSI (ref schema.go csi_volumes/csi_plugins)
        self.csi_volumes: dict[tuple[str, str], object] = {}  # (ns, id)
        self.csi_plugins: dict[str, object] = {}              # plugin id
        # native service catalog (the consul-integration redesign;
        # ref nomad/state service_registration table in later lines)
        self.services: dict[tuple[str, str, str], object] = {}
        # mesh authorization rules keyed (ns, source, destination)
        self.intentions: dict[tuple[str, str, str], object] = {}
        # autopilot (ref nomad/state/autopilot.go AutopilotConfig)
        self.autopilot_config: dict = {
            "CleanupDeadServers": True,
            "LastContactThresholdSec": 10.0,
            "ServerStabilizationTimeSec": 10.0,
        }
        # replicated RPC write-dedup acks (ISSUE 18): token -> commit
        # index, LRU-bounded. Written by the FSM when a raft entry
        # carries a `_dedup` stamp, so EVERY server (and a restored
        # snapshot) remembers which client requests already committed —
        # the failover half of rpc/dedup.py (the leader-local result
        # cache holds the full result blobs).
        self.rpc_dedup: "OrderedDict[str, int]" = OrderedDict()

        # secondary indexes
        self._allocs_by_node: dict[str, set[str]] = {}
        self._allocs_by_job: dict[tuple[str, str], set[str]] = {}
        self._allocs_by_eval: dict[str, set[str]] = {}
        self._evals_by_job: dict[tuple[str, str], set[str]] = {}
        # dense [N, R'] capacity/usage matrices, maintained incrementally —
        # the solver's input (see usage_index.py module docstring)
        self.usage = UsageIndex()

        # memoized point-in-time snapshot, valid until the next write
        # (ISSUE 5 satellite): every reader between two commits — the K
        # worker lanes of one coalesced micro-batch window, the plan
        # applier's per-batch SnapshotMinIndex, blocking-query fans —
        # shares ONE StateSnapshot construction instead of each paying
        # the full table copy. Safe because a StateSnapshot is read-only
        # by contract and stored objects are immutable-by-convention.
        self._snap_memo: Optional["StateSnapshot"] = None

        # event sink (wired to the event broker by the server)
        self.event_sinks: list[Callable[[str, str, int, object], None]] = []
        # batched sink twin (ISSUE 20): one call per apply-batch window
        # flush, carrying [(topic, etype, index, payload)] — the broker
        # publishes the whole window as ONE batch (one broker lock
        # round, one offer per subscriber). When empty, a window flush
        # falls back to the per-event sinks.
        self.event_batch_sinks: list[Callable[[list], None]] = []
        # apply-batch window state (ISSUE 20 group commit), guarded by
        # self._lock — the window HOLDS the lock for its whole extent
        # (that is exactly what makes the deferrals below invisible to
        # readers): depth of nested windows, buffered events, and
        # whether any commit happened inside the window.
        self._batch_depth = 0
        self._batch_events: list[tuple] = []
        self._batch_dirty = False
        # optional: the owning server/agent wires its logger in so sink
        # failures surface in the agent log (counted regardless)
        self.logger: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------------ core

    def latest_index(self) -> int:
        with self._lock:
            return self._index

    def table_index(self, table: str) -> int:
        with self._lock:
            return self._table_index.get(table, 0)

    def _bump(self, table: str, index: Optional[int] = None) -> int:
        """Advance the store to `index` (or next) for a write to `table`."""
        if index is None:
            index = self._index + 1
        self._index = max(self._index, index)
        self._table_index[table] = self._index
        # any write invalidates the shared snapshot memo — keyed on the
        # write GENERATION, not the index: a batched FSM entry applies
        # several writes at one index and each must displace the memo.
        # _bump is only ever called with self._lock held (every writer).
        # nomadlint: disable=LOCK001 — caller holds the write lock
        self._snap_memo = None
        return self._index

    def _commit(self) -> None:
        if self._batch_depth:
            # inside an apply-batch window: ONE wakeup at window exit
            # (blocking queries re-check their predicate anyway, and
            # the lock is held until the flush, so no reader can
            # observe the gap). _commit is only ever called with the
            # write lock held, like _bump above.
            # nomadlint: disable=LOCK001 — caller holds the write lock
            self._batch_dirty = True
            return
        self._cond.notify_all()

    def _emit(self, topic: str, etype: str, index: int, payload) -> None:
        if self._batch_depth:
            # inside an apply-batch window: buffer for ONE batched
            # publish at window exit (ISSUE 20)
            self._batch_events.append((topic, etype, index, payload))
            return
        for sink in self.event_sinks:
            try:
                sink(topic, etype, index, payload)
            except Exception as e:      # noqa: BLE001
                # a broken sink must not block commits, but a sink that
                # silently stops delivering is an invisible outage —
                # count it (EXC001; logger is optional, agents wire one)
                record_swallowed_error("state.emit", e, self.logger)

    @contextmanager
    def batch_window(self):
        """Hold the write lock across a batch of FSM applies and flush
        their side effects ONCE at exit (ISSUE 20 group commit): one
        condvar broadcast, one event-sink publish batch, and — because
        the lock never drops inside the window — one effective
        snapshot-memo rebuild for the whole batch instead of one per
        entry. Re-entrant (RLock + depth counter); the outermost exit
        flushes. Mutations inside the window are ordinary mutator
        calls; they re-enter the already-held lock."""
        with self._lock:
            self._batch_depth += 1
            try:
                yield self
            finally:
                self._batch_depth -= 1
                if self._batch_depth == 0:
                    self._flush_batch_locked()

    def _flush_batch_locked(self) -> None:
        events, self._batch_events = self._batch_events, []
        dirty, self._batch_dirty = self._batch_dirty, False
        if events:
            if self.event_batch_sinks:
                for sink in self.event_batch_sinks:
                    try:
                        sink(events)
                    except Exception as e:      # noqa: BLE001
                        record_swallowed_error("state.emit_batch", e,
                                               self.logger)
            else:
                for topic, etype, index, payload in events:
                    for sink in self.event_sinks:
                        try:
                            sink(topic, etype, index, payload)
                        except Exception as e:      # noqa: BLE001
                            record_swallowed_error("state.emit", e,
                                                   self.logger)
        if dirty or events:
            self._cond.notify_all()

    def snapshot(self) -> "StateSnapshot":
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> "StateSnapshot":
        snap = self._snap_memo
        if snap is None:
            snap = self._snap_memo = StateSnapshot(self)
        else:
            from ..metrics import metrics
            metrics.incr("nomad.state.snapshot_shared")
        return snap

    # -------------------------------------------------- rpc write dedup
    # (ISSUE 18) token -> commit index, written from NomadFSM.apply when
    # an entry carries a `_dedup` stamp. Deliberately NOT a _bump table:
    # a dedup record is metadata ABOUT an apply at `index`, not a write
    # of its own, and must not wake blocking queries or churn the memo.

    def record_rpc_dedup(self, index: int, token: str) -> None:
        with self._lock:
            dd = self.rpc_dedup
            dd[token] = index
            dd.move_to_end(token)
            while len(dd) > RPC_DEDUP_CAP:
                dd.popitem(last=False)

    def rpc_dedup_get(self, token: str) -> Optional[int]:
        with self._lock:
            return self.rpc_dedup.get(token)

    def rpc_dedup_len(self) -> int:
        with self._lock:
            return len(self.rpc_dedup)

    def fork(self) -> "StateStore":
        """Writable scratch copy for dry-run planning (the Job.Plan endpoint
        runs a real scheduler pass against a snapshot without touching Raft —
        ref nomad/job_endpoint.go Job.Plan). Shallow table copies are safe:
        stored objects are immutable-by-convention."""
        with self._lock:
            out = StateStore()
            out._index = self._index
            out._table_index = dict(self._table_index)
            out.nodes = dict(self.nodes)
            out.jobs = dict(self.jobs)
            out.job_versions = dict(self.job_versions)
            out.job_summaries = dict(self.job_summaries)
            out.evals = dict(self.evals)
            out.allocs = dict(self.allocs)
            out.deployments = dict(self.deployments)
            out.periodic_launches = dict(self.periodic_launches)
            out.acl_policies = dict(self.acl_policies)
            out.acl_tokens = dict(self.acl_tokens)
            out._acl_token_by_secret = dict(self._acl_token_by_secret)
            out.scheduler_config = self.scheduler_config
            out.namespaces = dict(self.namespaces)
            out.scaling_policies = dict(self.scaling_policies)
            out._scaling_policy_by_target = dict(self._scaling_policy_by_target)
            out.scaling_events = {k: {g: list(evs) for g, evs in v.items()}
                                  for k, v in self.scaling_events.items()}
            out.csi_volumes = dict(self.csi_volumes)
            out.csi_plugins = dict(self.csi_plugins)
            out.services = dict(self.services)
            out.intentions = dict(self.intentions)
            out.autopilot_config = dict(self.autopilot_config)
            out.usage = self.usage.copy()
            out._allocs_by_node = {k: set(v)
                                   for k, v in self._allocs_by_node.items()}
            out._allocs_by_job = {k: set(v)
                                  for k, v in self._allocs_by_job.items()}
            out._allocs_by_eval = {k: set(v)
                                   for k, v in self._allocs_by_eval.items()}
            out._evals_by_job = {k: set(v)
                                 for k, v in self._evals_by_job.items()}
            return out

    def snapshot_min_index(self, index: int, timeout: float = 5.0
                           ) -> "StateSnapshot":
        """Block until latest_index >= index, then snapshot
        (ref nomad/worker.go:536 snapshotMinIndex)."""
        from .. import faults
        faults.fire("state.snapshot_min_index")
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._index < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"timed out waiting for index {index} (at {self._index})")
                self._cond.wait(remaining)
            return self._snapshot_locked()

    def block_min_index(self, index: int, timeout: float = 60.0) -> int:
        """Blocking-query primitive: wait for any write past `index`."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._index <= index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._index
                self._cond.wait(remaining)
            return self._index

    # ----------------------------------------------------------------- nodes

    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            existing = self.nodes.get(node.id)
            node = node.copy()
            if existing:
                node.create_index = existing.create_index
                # preserve drain/eligibility set server-side unless provided
                if node.drain_strategy is None and existing.drain_strategy:
                    node.drain_strategy = existing.drain_strategy
                    node.scheduling_eligibility = existing.scheduling_eligibility
                if existing.flap_held_until:
                    # a flap hold survives re-registration (ISSUE 10):
                    # only the damper's re-admit or an operator
                    # eligibility write lifts it — a flapping agent
                    # re-registering must not wash its own hold away
                    node.flap_held_until = existing.flap_held_until
                    node.scheduling_eligibility = \
                        existing.scheduling_eligibility
            else:
                node.create_index = index
            node.modify_index = self._bump("nodes", index)
            self.nodes[node.id] = node
            self.usage.set_node(node)
            self._update_csi_plugins_from_node(index, node)
            self._emit("Node", "NodeRegistration", node.modify_index, node)
            self._commit()

    def delete_node(self, index: int, node_ids: list[str]) -> None:
        with self._lock:
            for nid in node_ids:
                self.nodes.pop(nid, None)
                self.usage.drop_node(nid)
                self._delete_node_from_csi_plugins(index, nid)
            self._bump("nodes", index)
            self._commit()

    def update_node_status(self, index: int, node_id: str, status: str,
                           updated_at: float = 0.0) -> None:
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            node = node.copy()
            node.status = status
            node.status_updated_at = updated_at
            node.modify_index = self._bump("nodes", index)
            self.nodes[node_id] = node
            self.usage.set_node_taint(node_id, node.ready())
            self._emit("Node", "NodeStatusUpdate", node.modify_index, node)
            self._commit()

    def update_node_status_batch(self, index: int, node_ids: list[str],
                                 status: str,
                                 updated_at: float = 0.0) -> int:
        """Batched status flip (ISSUE 10): one FSM entry marks a whole
        heartbeat-sweep's expired nodes, under ONE lock hold and one
        commit — the serial per-node sequence's exact final state (the
        storm differential in tests/test_node_storm.py pins byte
        equality). Nodes GC'd between expiry and commit are skipped.
        Returns the number of nodes actually updated."""
        n = 0
        with self._lock:
            idx = self._bump("nodes", index)
            for node_id in node_ids:
                node = self.nodes.get(node_id)
                if node is None:
                    continue
                node = node.copy()
                node.status = status
                node.status_updated_at = updated_at
                node.modify_index = idx
                self.nodes[node_id] = node
                self.usage.set_node_taint(node_id, node.ready())
                self._emit("Node", "NodeStatusUpdate", idx, node)
                n += 1
            self._commit()
        return n

    def update_node_drain(self, index: int, node_id: str, drain,
                          mark_eligible: bool = False) -> None:
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            node = node.copy()
            node.drain_strategy = drain
            if drain is not None:
                node.scheduling_eligibility = "ineligible"
            elif mark_eligible:
                node.scheduling_eligibility = "eligible"
            node.modify_index = self._bump("nodes", index)
            self.nodes[node_id] = node
            self.usage.set_node_taint(node_id, node.ready())
            self._emit("Node", "NodeDrain", node.modify_index, node)
            self._commit()

    def update_node_eligibility(self, index: int, node_id: str,
                                eligibility: str,
                                flap_until: Optional[float] = None) -> None:
        """`flap_until` is set by the flap damper (ISSUE 10): the hold
        deadline rides raft so a NEW leader can re-admit nodes a deposed
        damper held. Operator/plain eligibility writes clear it."""
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(f"node {node_id} not found")
            node = node.copy()
            node.scheduling_eligibility = eligibility
            node.flap_held_until = float(flap_until or 0.0)
            node.modify_index = self._bump("nodes", index)
            self.nodes[node_id] = node
            self.usage.set_node_taint(node_id, node.ready())
            self._commit()

    def node_by_id(self, node_id: str) -> Optional[Node]:
        with self._lock:
            return self.nodes.get(node_id)

    def iter_nodes(self) -> list[Node]:
        with self._lock:
            return list(self.nodes.values())

    def node_count(self) -> int:
        """O(1) fleet size — hot-path gates (the standby twin feed runs
        per replicated plan apply) must not copy the node table."""
        with self._lock:
            return len(self.nodes)

    # ------------------------------------------------------------------ jobs

    def upsert_job(self, index: int, job: Job, keep_version: bool = False) -> None:
        """Insert/update a job, maintaining version history and summary
        (ref state_store.go UpsertJob/upsertJobImpl)."""
        with self._lock:
            key = (job.namespace, job.id)
            existing = self.jobs.get(key)
            job = job.copy()
            if existing:
                job.create_index = existing.create_index
                job.job_modify_index = index
                if not keep_version:
                    job.version = existing.version + 1
            else:
                job.create_index = index
                job.job_modify_index = index
                job.version = 0
            job.modify_index = self._bump("jobs", index)
            if job.status not in (JOB_STATUS_DEAD,):
                job.status = self._compute_job_status(job)
            self.jobs[key] = job
            self.job_versions[(job.namespace, job.id, job.version)] = job
            self._prune_job_versions(job.namespace, job.id)
            self._ensure_summary(index, job)
            self._update_scaling_policies(index, job)
            self._emit("Job", "JobRegistered", job.modify_index, job)
            self._commit()

    def _compute_job_status(self, job: Job) -> str:
        """ref state_store.go getJobStatus: running if any live alloc; pending
        while evals are outstanding or nothing has run yet; dead once a job
        that had allocations has only terminal ones left."""
        if job.stop:
            return JOB_STATUS_DEAD
        if job.is_periodic() or job.is_parameterized():
            return JOB_STATUS_RUNNING
        key = (job.namespace, job.id)
        alloc_ids = self._allocs_by_job.get(key, ())
        for aid in alloc_ids:  # any live alloc => running
            if not self.allocs[aid].terminal_status():
                return JOB_STATUS_RUNNING
        for eid in self._evals_by_job.get(key, ()):
            ev = self.evals.get(eid)
            if ev is not None and not ev.terminal_status():
                return JOB_STATUS_PENDING
        if alloc_ids:
            return JOB_STATUS_DEAD
        return JOB_STATUS_PENDING

    def _prune_job_versions(self, ns: str, job_id: str, keep: int = 6) -> None:
        versions = sorted(v for (n, j, v) in self.job_versions
                          if n == ns and j == job_id)
        for v in versions[:-keep]:
            self.job_versions.pop((ns, job_id, v), None)

    def _ensure_summary(self, index: int, job: Job) -> None:
        key = (job.namespace, job.id)
        summ = self.job_summaries.get(key)
        summ = summ.copy() if summ else JobSummary(
            job_id=job.id, namespace=job.namespace, create_index=index)
        for tg in job.task_groups:
            summ.summary.setdefault(tg.name, TaskGroupSummary())
        summ.modify_index = index
        self.job_summaries[key] = summ

    def delete_job(self, index: int, ns: str, job_id: str) -> None:
        with self._lock:
            self.jobs.pop((ns, job_id), None)
            for k in [k for k in self.job_versions if k[0] == ns and k[1] == job_id]:
                self.job_versions.pop(k)
            self.job_summaries.pop((ns, job_id), None)
            self.periodic_launches.pop((ns, job_id), None)
            self.scaling_events.pop((ns, job_id), None)
            for tkey in [k for k in self._scaling_policy_by_target
                         if k[0] == ns and k[1] == job_id]:
                pid = self._scaling_policy_by_target.pop(tkey)
                self.scaling_policies.pop(pid, None)
                self._bump("scaling_policy", index)
            self._bump("jobs", index)
            self._emit("Job", "JobDeregistered", self._index, (ns, job_id))
            self._commit()

    def job_by_id(self, ns: str, job_id: str) -> Optional[Job]:
        with self._lock:
            return self.jobs.get((ns, job_id))

    def job_by_version(self, ns: str, job_id: str, version: int) -> Optional[Job]:
        with self._lock:
            return self.job_versions.get((ns, job_id, version))

    def job_versions_by_id(self, ns: str, job_id: str) -> list[Job]:
        with self._lock:
            out = [j for (n, i, _v), j in self.job_versions.items()
                   if n == ns and i == job_id]
            return sorted(out, key=lambda j: -j.version)

    def iter_jobs(self, ns: Optional[str] = None) -> list[Job]:
        with self._lock:
            return [j for j in self.jobs.values()
                    if ns is None or j.namespace == ns]

    def job_summary(self, ns: str, job_id: str) -> Optional[JobSummary]:
        with self._lock:
            return self.job_summaries.get((ns, job_id))

    # --------------------------------------------------------------- scaling

    def _update_scaling_policies(self, index: int, job: Job) -> None:
        """Sync the scaling_policy table with a job's scaling blocks (ref
        state_store.go updateJobScalingPolicies). Must hold self._lock."""
        from ..structs.scaling import policy_from_group
        live_targets = set()
        for tg in job.task_groups:
            pol = policy_from_group(job, tg)
            if pol is None:
                continue
            tkey = pol.target_key()
            live_targets.add(tkey)
            existing_id = self._scaling_policy_by_target.get(tkey)
            if existing_id is not None:
                existing = self.scaling_policies[existing_id]
                pol.id = existing.id
                pol.create_index = existing.create_index
                if (existing.min == pol.min and existing.max == pol.max
                        and existing.policy == pol.policy
                        and existing.enabled == pol.enabled
                        and existing.type == pol.type):
                    continue  # unchanged — keep modify_index stable
                pol.modify_index = self._bump("scaling_policy", index)
            else:
                pol.create_index = index
                pol.modify_index = self._bump("scaling_policy", index)
            self.scaling_policies[pol.id] = pol
            self._scaling_policy_by_target[tkey] = pol.id
        # drop policies for groups no longer in the job
        for tkey in [k for k in self._scaling_policy_by_target
                     if k[0] == job.namespace and k[1] == job.id
                     and k not in live_targets]:
            pid = self._scaling_policy_by_target.pop(tkey)
            self.scaling_policies.pop(pid, None)
            self._bump("scaling_policy", index)

    def iter_scaling_policies(self, ns: Optional[str] = None,
                              job_id: Optional[str] = None,
                              type_: Optional[str] = None) -> list:
        with self._lock:
            out = []
            for pol in self.scaling_policies.values():
                pns, pjob, _ = pol.target_key()
                if ns is not None and pns != ns:
                    continue
                if job_id is not None and pjob != job_id:
                    continue
                if type_ is not None and pol.type != type_:
                    continue
                out.append(pol)
            return sorted(out, key=lambda p: p.target_key())

    def scaling_policy_by_id(self, policy_id: str):
        with self._lock:
            return self.scaling_policies.get(policy_id)

    def scaling_policy_by_target(self, ns: str, job_id: str, group: str):
        with self._lock:
            pid = self._scaling_policy_by_target.get((ns, job_id, group))
            return self.scaling_policies.get(pid) if pid else None

    def upsert_scaling_event(self, index: int, ns: str, job_id: str,
                             group: str, event) -> None:
        """ref state_store.go UpsertScalingEvent — bounded trail per group."""
        from ..structs.scaling import JOB_TRACKED_SCALING_EVENTS
        with self._lock:
            event = event.copy()
            event.create_index = self._bump("scaling_event", index)
            groups = self.scaling_events.setdefault((ns, job_id), {})
            trail = groups.setdefault(group, [])
            trail.insert(0, event)
            del trail[JOB_TRACKED_SCALING_EVENTS:]
            self._commit()

    def scaling_events_by_job(self, ns: str, job_id: str) -> dict[str, list]:
        with self._lock:
            return {g: list(evs) for g, evs in
                    self.scaling_events.get((ns, job_id), {}).items()}

    # ------------------------------------------------------------------ CSI

    def _update_csi_plugins_from_node(self, index: int, node) -> None:
        """Fold one node's fingerprinted CSI plugins into the aggregated
        plugin table (ref state_store.go updateNodeCSIPlugins). Holds lock."""
        from ..structs.csi import CSIPlugin
        seen = set()
        for pid, info in {**node.csi_node_plugins,
                          **node.csi_controller_plugins}.items():
            seen.add(pid)
            plug = self.csi_plugins.get(pid)
            plug = plug.copy() if plug else CSIPlugin(
                id=pid, create_index=index)
            plug.provider = info.get("provider", plug.provider)
            plug.version = info.get("provider_version", plug.version)
            if info.get("requires_controller"):
                plug.controller_required = True
            if pid in node.csi_node_plugins:
                plug.nodes[node.id] = bool(
                    node.csi_node_plugins[pid].get("healthy", False))
            if pid in node.csi_controller_plugins:
                plug.controllers[node.id] = bool(
                    node.csi_controller_plugins[pid].get("healthy", False))
            plug.modify_index = self._bump("csi_plugins", index)
            self.csi_plugins[pid] = plug
        # node no longer fingerprints a plugin -> drop its contribution
        for pid in [p for p in self.csi_plugins if p not in seen]:
            plug = self.csi_plugins[pid]
            if node.id in plug.nodes or node.id in plug.controllers:
                plug = plug.copy()
                plug.nodes.pop(node.id, None)
                plug.controllers.pop(node.id, None)
                plug.modify_index = self._bump("csi_plugins", index)
                if plug.is_empty():
                    del self.csi_plugins[pid]
                else:
                    self.csi_plugins[pid] = plug

    def _delete_node_from_csi_plugins(self, index: int, node_id: str) -> None:
        for pid in list(self.csi_plugins):
            plug = self.csi_plugins[pid]
            if node_id in plug.nodes or node_id in plug.controllers:
                plug = plug.copy()
                plug.nodes.pop(node_id, None)
                plug.controllers.pop(node_id, None)
                self._bump("csi_plugins", index)
                if plug.is_empty():
                    del self.csi_plugins[pid]
                else:
                    self.csi_plugins[pid] = plug

    def upsert_csi_volume(self, index: int, vol) -> None:
        """ref state_store.go CSIVolumeRegister"""
        with self._lock:
            key = (vol.namespace, vol.id)
            existing = self.csi_volumes.get(key)
            vol = vol.copy()
            if existing:
                vol.create_index = existing.create_index
                # claims survive re-registration
                vol.read_claims = {k: v.copy() for k, v
                                   in existing.read_claims.items()}
                vol.write_claims = {k: v.copy() for k, v
                                    in existing.write_claims.items()}
            else:
                vol.create_index = index
            vol.modify_index = self._bump("csi_volumes", index)
            self.csi_volumes[key] = vol
            self._commit()

    def delete_csi_volume(self, index: int, ns: str, vol_id: str,
                          force: bool = False) -> None:
        """ref state_store.go CSIVolumeDeregister"""
        with self._lock:
            vol = self.csi_volumes.get((ns, vol_id))
            if vol is None:
                raise ValueError(f"volume {vol_id!r} not found")
            if vol.in_use() and not force:
                raise ValueError(f"volume {vol_id!r} is in use")
            del self.csi_volumes[(ns, vol_id)]
            self._bump("csi_volumes", index)
            self._commit()

    def csi_volume_claim(self, index: int, ns: str, vol_id: str,
                         claim) -> None:
        """Take or update one claim (ref state_store.go CSIVolumeClaim)."""
        from ..structs.csi import (
            CLAIM_WRITE, CLAIM_STATE_CONTROLLER_DETACHED,
            CLAIM_STATE_NODE_DETACHED, CLAIM_STATE_READY_TO_FREE,
        )
        with self._lock:
            vol = self.csi_volumes.get((ns, vol_id))
            if vol is None:
                raise ValueError(f"volume {vol_id!r} not found")
            vol = vol.copy()
            if claim.state == CLAIM_STATE_READY_TO_FREE:
                vol.read_claims.pop(claim.alloc_id, None)
                vol.write_claims.pop(claim.alloc_id, None)
            elif claim.state in (CLAIM_STATE_NODE_DETACHED,
                                 CLAIM_STATE_CONTROLLER_DETACHED):
                # detach progress: advance the EXISTING claim's state —
                # no mode/claim_ok checks (the slot is already held)
                for claims in (vol.read_claims, vol.write_claims):
                    cur = claims.get(claim.alloc_id)
                    if cur is not None:
                        cur = cur.copy()
                        cur.state = claim.state
                        claims[claim.alloc_id] = cur
            elif claim.mode == CLAIM_WRITE:
                if not vol.claim_ok(claim.mode) and \
                        claim.alloc_id not in vol.write_claims:
                    raise ValueError(
                        f"volume {vol_id!r} has no free write claims")
                vol.read_claims.pop(claim.alloc_id, None)
                vol.write_claims[claim.alloc_id] = claim.copy()
            else:
                if not vol.claim_ok(claim.mode):
                    raise ValueError(f"volume {vol_id!r} not readable")
                vol.read_claims[claim.alloc_id] = claim.copy()
            vol.modify_index = self._bump("csi_volumes", index)
            self.csi_volumes[(ns, vol_id)] = vol
            self._commit()

    def _csi_denormalize(self, vol):
        """Attach live plugin health to a volume copy at read time
        (ref state_store.go CSIVolumeDenormalize)."""
        plug = self.csi_plugins.get(vol.plugin_id)
        vol = vol.copy()
        if plug is not None:
            vol.controllers_healthy = plug.controllers_healthy
            vol.nodes_healthy = plug.nodes_healthy
            vol.controller_required = plug.controller_required
            vol.schedulable = plug.nodes_healthy > 0 and (
                not plug.controller_required or plug.controllers_healthy > 0)
        else:
            vol.schedulable = False
        return vol

    def csi_volume_by_id(self, ns: str, vol_id: str):
        with self._lock:
            vol = self.csi_volumes.get((ns, vol_id))
            return self._csi_denormalize(vol) if vol else None

    def iter_csi_volumes(self, ns: Optional[str] = None,
                         plugin_id: Optional[str] = None) -> list:
        with self._lock:
            return [self._csi_denormalize(v)
                    for v in self.csi_volumes.values()
                    if (ns is None or v.namespace == ns)
                    and (plugin_id is None or v.plugin_id == plugin_id)]

    def csi_plugin_by_id(self, plugin_id: str):
        with self._lock:
            return self.csi_plugins.get(plugin_id)

    def iter_csi_plugins(self) -> list:
        with self._lock:
            return sorted(self.csi_plugins.values(), key=lambda p: p.id)

    # ------------------------------------------------------------- services

    def upsert_service_registrations(self, index: int,
                                     instances: list) -> None:
        with self._lock:
            idx = self._bump("services", index)
            for inst in instances:
                inst = inst.copy()
                existing = self.services.get(inst.key())
                inst.create_index = existing.create_index if existing else idx
                inst.modify_index = idx
                self.services[inst.key()] = inst
            self._commit()

    def delete_service_registrations(self, index: int,
                                     alloc_id: str = "",
                                     keys: Optional[list] = None) -> None:
        with self._lock:
            doomed = list(keys or [])
            if alloc_id:
                doomed += [k for k in self.services if k[2] == alloc_id]
            for k in doomed:
                self.services.pop(tuple(k), None)
            if doomed:
                self._bump("services", index)
            self._commit()

    # ----------------------------------------------------------- intentions

    def upsert_intention(self, index: int, intention) -> None:
        with self._lock:
            idx = self._bump("intentions", index)
            it = intention.copy()
            existing = self.intentions.get(it.key())
            it.create_index = existing.create_index if existing else idx
            it.modify_index = idx
            self.intentions[it.key()] = it
            self._commit()

    def delete_intention(self, index: int, namespace: str, source: str,
                         destination: str) -> None:
        with self._lock:
            if self.intentions.pop((namespace, source, destination),
                                   None) is not None:
                self._bump("intentions", index)
                self._commit()

    def iter_intentions(self, namespace: Optional[str] = None) -> list:
        with self._lock:
            return [i for i in self.intentions.values()
                    if namespace in (None, i.namespace)]

    def intention_allowed(self, namespace: str, source: str,
                          destination: str) -> bool:
        from ..integrations.services import intention_allowed
        with self._lock:
            return intention_allowed(self.intentions.values(), namespace,
                                     source, destination)

    def services_by_name(self, ns: str, name: str) -> list:
        with self._lock:
            return [s for s in self.services.values()
                    if s.namespace == ns and s.service_name == name]

    def iter_services(self, ns: Optional[str] = None) -> list:
        with self._lock:
            return [s for s in self.services.values()
                    if ns is None or s.namespace == ns]

    # ------------------------------------------------------------ autopilot

    def get_autopilot_config(self) -> dict:
        with self._lock:
            return dict(self.autopilot_config)

    def set_autopilot_config(self, index: int, config: dict) -> None:
        with self._lock:
            self.autopilot_config = {**self.autopilot_config, **config}
            self._bump("autopilot", index)
            self._commit()

    def update_job_stability(self, index: int, ns: str, job_id: str,
                             version: int, stable: bool) -> None:
        """ref state_store.go UpdateJobStability."""
        with self._lock:
            j = self.job_versions.get((ns, job_id, version))
            if j is None:
                return  # validated at the endpoint; FSM apply must not raise
            j = j.copy()
            j.stable = stable
            j.modify_index = self._bump("jobs", index)
            self.job_versions[(ns, job_id, version)] = j
            cur = self.jobs.get((ns, job_id))
            if cur is not None and cur.version == version:
                cur = cur.copy()
                cur.stable = stable
                cur.modify_index = j.modify_index
                self.jobs[(ns, job_id)] = cur
            self._commit()

    # ----------------------------------------------------------------- evals

    def upsert_evals(self, index: int, evals: list[Evaluation]) -> None:
        with self._lock:
            idx = self._bump("evals", index)
            for ev in evals:
                ev = ev.copy()
                existing = self.evals.get(ev.id)
                ev.create_index = existing.create_index if existing else idx
                ev.modify_index = idx
                self._index_eval(ev)
                self.evals[ev.id] = ev
                self._update_summary_queued(idx, ev)
                self._emit("Evaluation", "EvaluationUpdated", idx, ev)
            self._commit()

    def _index_eval(self, ev: Evaluation) -> None:
        key = (ev.namespace, ev.job_id)
        self._evals_by_job.setdefault(key, set()).add(ev.id)

    def _update_summary_queued(self, index: int, ev: Evaluation) -> None:
        key = (ev.namespace, ev.job_id)
        summ = self.job_summaries.get(key)
        if summ is None or not ev.queued_allocations:
            return
        summ = summ.copy()
        for tg, n in ev.queued_allocations.items():
            summ.summary.setdefault(tg, TaskGroupSummary()).queued = n
        summ.modify_index = index
        self.job_summaries[key] = summ

    def delete_evals(self, index: int, eval_ids: list[str],
                     alloc_ids: list[str] = ()) -> None:
        with self._lock:
            for eid in eval_ids:
                ev = self.evals.pop(eid, None)
                if ev:
                    s = self._evals_by_job.get((ev.namespace, ev.job_id))
                    if s:
                        s.discard(eid)
            for aid in alloc_ids:
                self._delete_alloc(aid)
            self._bump("evals", index)
            self._commit()

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        with self._lock:
            return self.evals.get(eval_id)

    def evals_by_job(self, ns: str, job_id: str) -> list[Evaluation]:
        with self._lock:
            return [self.evals[e] for e in self._evals_by_job.get((ns, job_id), ())
                    if e in self.evals]

    def iter_evals(self) -> list[Evaluation]:
        with self._lock:
            return list(self.evals.values())

    # ---------------------------------------------------------------- allocs

    def upsert_allocs(self, index: int, allocs: list[Allocation]) -> None:
        with self._lock:
            idx = self._bump("allocs", index)
            for alloc in allocs:
                self._upsert_alloc_locked(idx, alloc)
            self._commit()

    def _upsert_alloc_locked(self, idx: int, alloc: Allocation,
                             fresh: bool = False,
                             summary_cache: Optional[dict] = None,
                             skip_summary: bool = False) -> None:
        existing = self.allocs.get(alloc.id)
        if not (fresh and existing is None):
            # defensive copy; skipped for server-generated placements that
            # are fresh objects already (plan apply fast path)
            alloc = alloc.copy()
        if existing:
            alloc.create_index = existing.create_index
            # client-only fields are not clobbered by server-side upserts
            # (ref state_store.go UpsertAllocs: preserves client status unless set)
            if alloc.client_status == ALLOC_CLIENT_PENDING and \
               existing.client_status != ALLOC_CLIENT_PENDING and \
               alloc.desired_status != existing.desired_status:
                alloc.client_status = existing.client_status
                alloc.task_states = existing.task_states
            if alloc.job is None:
                alloc.job = existing.job
        else:
            alloc.create_index = idx
        alloc.modify_index = idx
        self.allocs[alloc.id] = alloc
        self._index_alloc(alloc)
        self.usage.set_alloc(alloc)
        if not skip_summary:
            self._reconcile_summary(idx, existing, alloc, summary_cache)
        self._emit("Allocation", "AllocationUpdated", idx, alloc)

    def _index_alloc(self, alloc: Allocation) -> None:
        self._allocs_by_node.setdefault(alloc.node_id, set()).add(alloc.id)
        self._allocs_by_job.setdefault(
            (alloc.namespace, alloc.job_id), set()).add(alloc.id)
        self._allocs_by_eval.setdefault(alloc.eval_id, set()).add(alloc.id)

    def _delete_alloc(self, alloc_id: str) -> None:
        alloc = self.allocs.pop(alloc_id, None)
        if not alloc:
            return
        self.usage.drop_alloc(alloc_id)
        for idx_map, key in ((self._allocs_by_node, alloc.node_id),
                             (self._allocs_by_job, (alloc.namespace, alloc.job_id)),
                             (self._allocs_by_eval, alloc.eval_id)):
            s = idx_map.get(key)
            if s:
                s.discard(alloc_id)

    _SUMMARY_FIELDS = {
        ALLOC_CLIENT_PENDING: "starting",
        ALLOC_CLIENT_RUNNING: "running",
        ALLOC_CLIENT_COMPLETE: "complete",
        ALLOC_CLIENT_FAILED: "failed",
        ALLOC_CLIENT_LOST: "lost",
        ALLOC_CLIENT_UNKNOWN: "unknown",
    }

    def _reconcile_summary(self, index: int, old: Optional[Allocation],
                           new: Allocation,
                           cache: Optional[dict] = None) -> None:
        """Maintain per-TG client-status counts
        (ref state_store.go updateSummaryWithAlloc). `cache` holds one
        already-copied summary per job for batch writes (plan apply), so a
        50k-alloc plan pays one summary copy, not 50k."""
        key = (new.namespace, new.job_id)
        summ = cache.get(key) if cache is not None else None
        if summ is None:
            summ = self.job_summaries.get(key)
            if summ is None:
                return
            summ = summ.copy()
            if cache is not None:
                cache[key] = summ
        tg = summ.summary.setdefault(new.task_group, TaskGroupSummary())
        if old is not None:
            f = self._SUMMARY_FIELDS.get(old.client_status)
            if f:
                setattr(tg, f, max(0, getattr(tg, f) - 1))
        f = self._SUMMARY_FIELDS.get(new.client_status)
        if f:
            setattr(tg, f, getattr(tg, f) + 1)
        summ.modify_index = index
        self.job_summaries[key] = summ

    def reconcile_job_summaries(self, index: int) -> None:
        """Rebuild every job summary from the live alloc set (ref
        state_store.go ReconcileJobSummaries, driven by
        PUT /v1/system/reconcile/summaries) — the repair path for
        summaries that drifted through bugs or partial restores."""
        with self._lock:
            idx = self._bump("job_summary", index)
            rebuilt: dict[tuple, JobSummary] = {}
            for (ns, job_id), job in self.jobs.items():
                summ = JobSummary(job_id=job_id, namespace=ns,
                                  create_index=idx, modify_index=idx)
                for tg in job.task_groups:
                    summ.summary.setdefault(tg.name, TaskGroupSummary())
                rebuilt[(ns, job_id)] = summ
            for alloc in self.allocs.values():
                summ = rebuilt.get((alloc.namespace, alloc.job_id))
                if summ is None:
                    continue
                tg = summ.summary.setdefault(alloc.task_group,
                                             TaskGroupSummary())
                f = self._SUMMARY_FIELDS.get(alloc.client_status)
                if f:
                    setattr(tg, f, getattr(tg, f) + 1)
            # queued counts are eval-owned state, not derivable from
            # allocs — carry them over from the old summaries
            for key, summ in rebuilt.items():
                old = self.job_summaries.get(key)
                if old is None:
                    continue
                summ.create_index = old.create_index
                for name, tgs in summ.summary.items():
                    old_tg = old.summary.get(name)
                    if old_tg is not None:
                        tgs.queued = old_tg.queued
            self.job_summaries = rebuilt
            self._commit()

    def update_allocs_from_client(self, index: int,
                                  allocs: list[Allocation]) -> None:
        """Client status updates: merge client-owned fields onto stored allocs
        (ref state_store.go UpdateAllocsFromClient/nestedUpdateAllocFromClient)."""
        with self._lock:
            idx = self._bump("allocs", index)
            for update in allocs:
                existing = self.allocs.get(update.id)
                if existing is None:
                    continue
                alloc = existing.copy()
                alloc.client_status = update.client_status
                alloc.client_description = update.client_description
                alloc.task_states = dict(update.task_states)
                alloc.network_status = update.network_status
                if update.deployment_status is not None:
                    # deployment health accounting rides the client update
                    # (ref state_store.go nestedUpdateAllocFromClient ->
                    #  updateDeploymentWithAlloc)
                    was = (existing.deployment_status.healthy
                           if existing.deployment_status else None)
                    now_h = update.deployment_status.healthy
                    alloc.deployment_status = update.deployment_status
                    if alloc.deployment_id and was != now_h and \
                       now_h is not None:
                        d = self.deployments.get(alloc.deployment_id)
                        if d is not None and d.active():
                            d = d.copy()
                            st = d.task_groups.get(alloc.task_group)
                            if st is not None:
                                if was is None:
                                    if now_h:
                                        st.healthy_allocs += 1
                                    else:
                                        st.unhealthy_allocs += 1
                                elif now_h:
                                    st.healthy_allocs += 1
                                    st.unhealthy_allocs -= 1
                                else:
                                    st.healthy_allocs -= 1
                                    st.unhealthy_allocs += 1
                            d.modify_index = idx
                            self.deployments[d.id] = d
                alloc.modify_index = idx
                alloc.modify_time_unix = update.modify_time_unix or time.time()
                self.allocs[alloc.id] = alloc
                self.usage.set_alloc(alloc)
                self._reconcile_summary(idx, existing, alloc)
                self._emit("Allocation", "AllocationUpdated", idx, alloc)
                # job status may flip (e.g. batch job completes)
                job = self.jobs.get((alloc.namespace, alloc.job_id))
                if job is not None:
                    status = self._compute_job_status(job)
                    if status != job.status:
                        job = job.copy()
                        job.status = status
                        job.modify_index = idx
                        self.jobs[(job.namespace, job.id)] = job
            self._commit()

    def update_alloc_desired_transitions(
            self, index: int, transitions: dict[str, object],
            evals: list[Evaluation] = ()) -> None:
        """Drainer entry point (ref state_store.go
        UpdateAllocsDesiredTransitions)."""
        with self._lock:
            idx = self._bump("allocs", index)
            for alloc_id, transition in transitions.items():
                existing = self.allocs.get(alloc_id)
                if existing is None:
                    continue
                alloc = existing.copy()
                alloc.desired_transition = transition
                alloc.modify_index = idx
                self.allocs[alloc_id] = alloc
            for ev in evals:
                ev = ev.copy()
                ev.create_index = idx
                ev.modify_index = idx
                self.evals[ev.id] = ev
                self._index_eval(ev)
            self._commit()

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        with self._lock:
            return self.allocs.get(alloc_id)

    def allocs_by_node(self, node_id: str) -> list[Allocation]:
        with self._lock:
            return [self.allocs[a] for a in self._allocs_by_node.get(node_id, ())
                    if a in self.allocs]

    def allocs_by_node_terminal(self, node_id: str,
                                terminal: bool) -> list[Allocation]:
        return [a for a in self.allocs_by_node(node_id)
                if a.terminal_status() == terminal]

    def allocs_by_job(self, ns: str, job_id: str,
                      anyCreateIndex: bool = True) -> list[Allocation]:
        with self._lock:
            return [self.allocs[a]
                    for a in self._allocs_by_job.get((ns, job_id), ())
                    if a in self.allocs]

    def allocs_by_eval(self, eval_id: str) -> list[Allocation]:
        with self._lock:
            return [self.allocs[a] for a in self._allocs_by_eval.get(eval_id, ())
                    if a in self.allocs]

    def namespace_alloc_counts(self) -> dict[str, int]:
        """Per-namespace allocation counts off the job index — the
        per-tenant usage signal the convex tier's quota budget reads
        (ISSUE 19). Counts index membership (includes recently-stopped
        allocs until GC), so it is a smoothed usage signal, not an exact
        running-instance census — quotas gate NEW placements, where
        over-counting errs safe."""
        with self._lock:
            counts: dict[str, int] = {}
            for (ns, _job), ids in self._allocs_by_job.items():
                counts[ns] = counts.get(ns, 0) + len(ids)
            return counts

    def iter_allocs(self) -> list[Allocation]:
        with self._lock:
            return list(self.allocs.values())

    # ------------------------------------------------------------ plan apply

    def upsert_plan_results_batch(self, index: int, results) -> None:
        """Apply a coalesced commit batch's plan results in list order
        under ONE lock hold (the lock is reentrant): all plans of the
        entry share `index`, so a blocking reader (`snapshot_min_index`,
        `block_min_index`) that wakes on the index must see the WHOLE
        entry — releasing the lock between per-plan transactions would
        let it observe index N with later plans of N still invisible,
        and their same-index writes would never re-wake it."""
        with self._lock:
            for result in results:
                self.upsert_plan_results(index, result)

    def upsert_plan_results(self, index: int, result) -> None:
        """Atomically apply a committed plan (ref nomad/fsm.go:998
        applyPlanResults + state_store.go UpsertPlanResults).

        `result` is an ApplyPlanResultsRequest-shaped object with:
        alloc_updates (stops), alloc_placements, alloc_preemptions,
        deployment, deployment_updates, eval_id, nodes_to_preempt.
        """
        from ..metrics import metrics
        with self._lock, metrics.measure("nomad.state.upsert_plan_results"):
            idx = self._bump("allocs", index)
            summary_cache: dict = {}
            now = time.time()
            for alloc in result.alloc_updates:      # stopped/updated allocs
                self._upsert_alloc_locked(idx, alloc,
                                          summary_cache=summary_cache)
            # fresh placements (all client-status pending) aggregate into
            # one summary bump per (job, tg) instead of 50k copies/updates;
            # the store writes run inline (no per-alloc function call) with
            # the index maps and sinks hoisted out of the loop
            fresh_counts: dict[tuple, int] = {}
            allocs_map = self.allocs
            by_node = self._allocs_by_node
            by_job = self._allocs_by_job
            by_eval = self._allocs_by_eval
            usage = self.usage
            sinks = self.event_sinks
            # index-map membership is accumulated per key and bulk-merged
            # after the loop (set.update beats 50k .add calls), and the
            # usage matrix takes the whole batch at once — together the
            # largest slice of the 50k-plan commit (VERDICT r4 #5)
            fresh: list = []
            node_acc: dict[str, list] = {}
            job_acc: dict[tuple, list] = {}
            eval_acc: dict[str, list] = {}
            for alloc in result.alloc_placements:   # new placements
                if alloc.create_time_unix == 0.0:
                    alloc.create_time_unix = now
                alloc.modify_time_unix = alloc.create_time_unix
                aid = alloc.id
                if aid not in allocs_map and \
                        alloc.client_status == ALLOC_CLIENT_PENDING:
                    key = (alloc.namespace, alloc.job_id, alloc.task_group)
                    fresh_counts[key] = fresh_counts.get(key, 0) + 1
                    alloc.create_index = idx
                    alloc.modify_index = idx
                    allocs_map[aid] = alloc
                    node_acc.setdefault(alloc.node_id, []).append(aid)
                    job_acc.setdefault(
                        (alloc.namespace, alloc.job_id), []).append(aid)
                    eval_acc.setdefault(alloc.eval_id, []).append(aid)
                    fresh.append(alloc)
                    if sinks:
                        self._emit("Allocation", "AllocationUpdated", idx,
                                   alloc)
                else:
                    self._upsert_alloc_locked(idx, alloc, fresh=True,
                                              summary_cache=summary_cache)
            for acc, index_map in ((node_acc, by_node), (job_acc, by_job),
                                   (eval_acc, by_eval)):
                for k, ids in acc.items():
                    members = index_map.get(k)
                    if members is None:
                        index_map[k] = set(ids)
                    else:
                        members.update(ids)
            usage.add_fresh_batch(fresh)
            for (ns, job_id, tg_name), cnt in fresh_counts.items():
                jkey = (ns, job_id)
                summ = summary_cache.get(jkey)
                if summ is None:
                    summ = self.job_summaries.get(jkey)
                    if summ is None:
                        continue
                    summ = summ.copy()
                    summary_cache[jkey] = summ
                tg = summ.summary.setdefault(tg_name, TaskGroupSummary())
                tg.starting += cnt
                summ.modify_index = idx
                self.job_summaries[jkey] = summ
            for alloc in result.alloc_preemptions:
                self._upsert_alloc_locked(idx, alloc,
                                          summary_cache=summary_cache)
            if result.deployment is not None:
                self._upsert_deployment_locked(idx, result.deployment)
            for du in result.deployment_updates:
                self._apply_deployment_update_locked(idx, du)
            # deployment placement bookkeeping (ref state_store.go
            # updateDeploymentWithAlloc)
            for alloc in result.alloc_placements:
                if not alloc.deployment_id:
                    continue
                d = self.deployments.get(alloc.deployment_id)
                if d is None:
                    continue
                d = d.copy()
                ds = d.task_groups.get(alloc.task_group)
                if ds is not None:
                    ds.placed_allocs += 1
                    if alloc.deployment_status is not None and \
                       alloc.deployment_status.canary and \
                       alloc.id not in ds.placed_canaries:
                        ds.placed_canaries.append(alloc.id)
                d.modify_index = idx
                self.deployments[d.id] = d
            # refresh job status
            job = None
            if result.alloc_placements:
                a0 = result.alloc_placements[0]
                job = self.jobs.get((a0.namespace, a0.job_id))
            if job is not None and job.status != JOB_STATUS_RUNNING and not job.stop:
                job = job.copy()
                job.status = JOB_STATUS_RUNNING
                job.modify_index = idx
                self.jobs[(job.namespace, job.id)] = job
            self._commit()

    # ------------------------------------------------------------ deployments

    def upsert_deployment(self, index: int, deployment: Deployment) -> None:
        with self._lock:
            idx = self._bump("deployment", index)
            self._upsert_deployment_locked(idx, deployment)
            self._commit()

    def _upsert_deployment_locked(self, idx: int, deployment: Deployment) -> None:
        existing = self.deployments.get(deployment.id)
        deployment = deployment.copy()
        deployment.create_index = existing.create_index if existing else idx
        deployment.modify_index = idx
        self.deployments[deployment.id] = deployment
        self._emit("Deployment", "DeploymentStatusUpdate", idx, deployment)

    def _apply_deployment_update_locked(self, idx: int, du) -> None:
        d = self.deployments.get(du.deployment_id)
        if d is None:
            return
        d = d.copy()
        d.status = du.status
        d.status_description = du.status_description
        d.modify_index = idx
        self.deployments[d.id] = d
        # a successful deployment marks its job version stable — the anchor
        # auto-revert rolls back to (ref deploymentwatcher SetJobStable)
        if du.status == "successful":
            vkey = (d.namespace, d.job_id, d.job_version)
            job = self.job_versions.get(vkey)
            if job is not None and not job.stable:
                job = job.copy()
                job.stable = True
                self.job_versions[vkey] = job
                current = self.jobs.get((d.namespace, d.job_id))
                if current is not None and current.version == d.job_version:
                    cur = current.copy()
                    cur.stable = True
                    self.jobs[(d.namespace, d.job_id)] = cur
        self._emit("Deployment", "DeploymentStatusUpdate", idx, d)

    def update_deployment_status(self, index: int, du,
                                 job: Optional[Job] = None,
                                 eval: Optional[Evaluation] = None) -> None:
        with self._lock:
            idx = self._bump("deployment", index)
            self._apply_deployment_update_locked(idx, du)
            if job is not None:
                self.upsert_job_locked_helper(idx, job)
            if eval is not None:
                ev = eval.copy()
                ev.create_index = idx
                ev.modify_index = idx
                self.evals[ev.id] = ev
                self._index_eval(ev)
            self._commit()

    def upsert_job_locked_helper(self, idx: int, job: Job) -> None:
        key = (job.namespace, job.id)
        existing = self.jobs.get(key)
        job = job.copy()
        if existing:
            job.create_index = existing.create_index
            job.version = existing.version + 1
        job.modify_index = idx
        self.jobs[key] = job
        self.job_versions[(job.namespace, job.id, job.version)] = job

    def update_deployment_alloc_health(self, index: int, deployment_id: str,
                                       healthy: list[str], unhealthy: list[str],
                                       timestamp: float = 0.0) -> None:
        """ref state_store.go UpdateDeploymentAllocHealth"""
        from ..structs import AllocDeploymentStatus
        with self._lock:
            idx = self._bump("deployment", index)
            d = self.deployments.get(deployment_id)
            for aid, is_healthy in [(a, True) for a in healthy] + \
                                   [(a, False) for a in unhealthy]:
                alloc = self.allocs.get(aid)
                if alloc is None:
                    continue
                old = alloc
                alloc = alloc.copy()
                ds = alloc.deployment_status or AllocDeploymentStatus()
                was = ds.healthy
                ds.healthy = is_healthy
                ds.timestamp_unix = timestamp or time.time()
                ds.modify_index = idx
                alloc.deployment_status = ds
                alloc.modify_index = idx
                self.allocs[aid] = alloc
                if d is not None and alloc.deployment_id == deployment_id:
                    d = d.copy()
                    state = d.task_groups.get(alloc.task_group)
                    if state is not None:
                        if was is None:
                            if is_healthy:
                                state.healthy_allocs += 1
                            else:
                                state.unhealthy_allocs += 1
                        elif was != is_healthy:
                            if is_healthy:
                                state.healthy_allocs += 1
                                state.unhealthy_allocs -= 1
                            else:
                                state.healthy_allocs -= 1
                                state.unhealthy_allocs += 1
                    d.modify_index = idx
                    self.deployments[d.id] = d
                self._emit("Allocation", "AllocationUpdated", idx, alloc)
            self._commit()

    def update_deployment_promotion(self, index: int, deployment_id: str,
                                    groups: Optional[list[str]] = None) -> None:
        with self._lock:
            idx = self._bump("deployment", index)
            d = self.deployments.get(deployment_id)
            if d is None:
                raise KeyError(f"deployment {deployment_id} not found")
            d = d.copy()
            for name, state in d.task_groups.items():
                if groups is None or name in groups:
                    state.promoted = True
            d.modify_index = idx
            self.deployments[d.id] = d
            # canary allocs get their canary flag cleared on promote via
            # deployment watcher-created eval; state keeps alloc flags as-is
            self._commit()

    def delete_deployments(self, index: int, deployment_ids: list[str]) -> None:
        with self._lock:
            for did in deployment_ids:
                self.deployments.pop(did, None)
            self._bump("deployment", index)
            self._commit()

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        with self._lock:
            return self.deployments.get(deployment_id)

    def deployments_by_job(self, ns: str, job_id: str) -> list[Deployment]:
        with self._lock:
            return [d for d in self.deployments.values()
                    if d.namespace == ns and d.job_id == job_id]

    def latest_deployment_by_job(self, ns: str, job_id: str
                                 ) -> Optional[Deployment]:
        ds = self.deployments_by_job(ns, job_id)
        if not ds:
            return None
        return max(ds, key=lambda d: d.create_index)

    def iter_deployments(self) -> list[Deployment]:
        with self._lock:
            return list(self.deployments.values())

    # -------------------------------------------------------- periodic/config

    def upsert_periodic_launch(self, index: int, ns: str, job_id: str,
                               launch_time: float) -> None:
        with self._lock:
            idx = self._bump("periodic_launch", index)
            self.periodic_launches[(ns, job_id)] = {
                "namespace": ns, "id": job_id, "launch": launch_time,
                "modify_index": idx}
            self._commit()

    def periodic_launch_by_id(self, ns: str, job_id: str) -> Optional[dict]:
        with self._lock:
            return self.periodic_launches.get((ns, job_id))

    def set_scheduler_config(self, index: int,
                             config: SchedulerConfiguration) -> None:
        with self._lock:
            import dataclasses as _dc
            config = _dc.replace(config)
            config.modify_index = self._bump("scheduler_config", index)
            self.scheduler_config = config
            self._commit()

    def get_scheduler_config(self) -> SchedulerConfiguration:
        with self._lock:
            return self.scheduler_config

    # ------------------------------------------------------------------ ACL
    # ref nomad/state/state_store.go ACL tables (acl_policy, acl_token)

    def upsert_acl_policies(self, index: int, policies: list) -> None:
        with self._lock:
            idx = self._bump("acl_policy", index)
            for pol in policies:
                pol = pol.copy()
                existing = self.acl_policies.get(pol.name)
                pol.create_index = existing.create_index if existing else idx
                pol.modify_index = idx
                self.acl_policies[pol.name] = pol
            self._commit()

    def delete_acl_policies(self, index: int, names: list[str]) -> None:
        with self._lock:
            self._bump("acl_policy", index)
            for name in names:
                self.acl_policies.pop(name, None)
            self._commit()

    def acl_policy_by_name(self, name: str):
        with self._lock:
            return self.acl_policies.get(name)

    def iter_acl_policies(self) -> list:
        with self._lock:
            return sorted(self.acl_policies.values(), key=lambda p: p.name)

    def upsert_acl_tokens(self, index: int, tokens: list) -> None:
        with self._lock:
            idx = self._bump("acl_token", index)
            for tok in tokens:
                tok = tok.copy()
                existing = self.acl_tokens.get(tok.accessor_id)
                tok.create_index = (existing.create_index if existing
                                    else idx)
                tok.modify_index = idx
                if existing and existing.secret_id != tok.secret_id:
                    self._acl_token_by_secret.pop(existing.secret_id, None)
                self.acl_tokens[tok.accessor_id] = tok
                self._acl_token_by_secret[tok.secret_id] = tok.accessor_id
            self._commit()

    def delete_acl_tokens(self, index: int, accessor_ids: list[str]) -> None:
        with self._lock:
            self._bump("acl_token", index)
            for aid in accessor_ids:
                tok = self.acl_tokens.pop(aid, None)
                if tok is not None:
                    self._acl_token_by_secret.pop(tok.secret_id, None)
            self._commit()

    def acl_token_by_accessor(self, accessor_id: str):
        with self._lock:
            return self.acl_tokens.get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        with self._lock:
            aid = self._acl_token_by_secret.get(secret_id)
            return self.acl_tokens.get(aid) if aid else None

    def iter_acl_tokens(self) -> list:
        with self._lock:
            return sorted(self.acl_tokens.values(),
                          key=lambda t: t.create_index)

    # ------------------------------------------------------------ namespaces

    def upsert_namespaces(self, index: int, namespaces: list[dict]) -> None:
        with self._lock:
            self._bump("namespaces", index)
            for ns in namespaces:
                self.namespaces[ns["name"]] = dict(ns)
            self._commit()

    def delete_namespaces(self, index: int, names: list[str]) -> None:
        with self._lock:
            self._bump("namespaces", index)
            for name in names:
                if name != "default":   # request validation lives in Server
                    self.namespaces.pop(name, None)
            self._commit()

    def namespace_by_name(self, name: str) -> Optional[dict]:
        with self._lock:
            return self.namespaces.get(name)

    def iter_namespaces(self) -> list[dict]:
        with self._lock:
            return sorted(self.namespaces.values(), key=lambda n: n["name"])


class StateSnapshot:
    """Point-in-time read-only view. Shallow dict copies are safe because
    stored objects are immutable-by-convention (writers always insert fresh
    copies)."""

    def __init__(self, store: StateStore):
        self.index = store._index
        self.nodes = dict(store.nodes)
        self.jobs = dict(store.jobs)
        self.job_versions = dict(store.job_versions)
        self.evals = dict(store.evals)
        self.allocs = dict(store.allocs)
        self.job_summaries = dict(store.job_summaries)
        self.deployments = dict(store.deployments)
        self.scheduler_config = store.scheduler_config
        self.csi_volumes = dict(store.csi_volumes)
        self.csi_plugins = dict(store.csi_plugins)
        self._allocs_by_node = {k: set(v) for k, v in store._allocs_by_node.items()}
        self._allocs_by_job = {k: set(v) for k, v in store._allocs_by_job.items()}
        self._evals_by_job = {k: set(v) for k, v in store._evals_by_job.items()}
        self.usage = store.usage.view()

    # read API mirrors the scheduler State interface (ref scheduler/scheduler.go:66)

    def latest_index(self) -> int:
        return self.index

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self.nodes.get(node_id)

    def csi_volume_by_id(self, ns: str, vol_id: str):
        return self.csi_volumes.get((ns, vol_id))

    def iter_nodes(self) -> list[Node]:
        return list(self.nodes.values())

    def iter_jobs(self, ns: Optional[str] = None) -> list[Job]:
        return [j for j in self.jobs.values()
                if ns is None or j.namespace == ns]

    def iter_evals(self) -> list[Evaluation]:
        return list(self.evals.values())

    def iter_allocs(self) -> list[Allocation]:
        return list(self.allocs.values())

    def job_summary(self, ns: str, job_id: str) -> Optional[JobSummary]:
        return self.job_summaries.get((ns, job_id))

    def ready_nodes_in_dcs(self, datacenters: Iterable[str]) -> list[Node]:
        dcs = set(datacenters)
        return [n for n in self.nodes.values()
                if n.ready() and n.datacenter in dcs]

    def job_by_id(self, ns: str, job_id: str) -> Optional[Job]:
        return self.jobs.get((ns, job_id))

    def job_by_version(self, ns: str, job_id: str, version: int) -> Optional[Job]:
        return self.job_versions.get((ns, job_id, version))

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self.evals.get(eval_id)

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self.allocs.get(alloc_id)

    def allocs_by_node(self, node_id: str) -> list[Allocation]:
        return [self.allocs[a] for a in self._allocs_by_node.get(node_id, ())
                if a in self.allocs]

    def allocs_by_job(self, ns: str, job_id: str) -> list[Allocation]:
        return [self.allocs[a] for a in self._allocs_by_job.get((ns, job_id), ())
                if a in self.allocs]

    def evals_by_job(self, ns: str, job_id: str) -> list[Evaluation]:
        return [self.evals[e] for e in self._evals_by_job.get((ns, job_id), ())
                if e in self.evals]

    def namespace_alloc_counts(self) -> dict[str, int]:
        """Snapshot twin of StateStore.namespace_alloc_counts — the
        convex quota budget reads whichever state view the eval holds."""
        counts: dict[str, int] = {}
        for (ns, _job), ids in self._allocs_by_job.items():
            counts[ns] = counts.get(ns, 0) + len(ids)
        return counts

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self.deployments.get(deployment_id)

    def deployments_by_job(self, ns: str, job_id: str) -> list[Deployment]:
        return [d for d in self.deployments.values()
                if d.namespace == ns and d.job_id == job_id]

    def latest_deployment_by_job(self, ns: str, job_id: str
                                 ) -> Optional[Deployment]:
        ds = [d for d in self.deployments.values()
              if d.namespace == ns and d.job_id == job_id]
        return max(ds, key=lambda d: d.create_index) if ds else None

    def get_scheduler_config(self) -> SchedulerConfiguration:
        return self.scheduler_config
