"""Deterministic fault injection (ISSUE 3 tentpole).

A TPU-backed control plane inherits a failure domain the host reference
never had: device dispatch can OOM, hang, or lose the accelerator
mid-solve. Gavel (arXiv:2008.09213) and Tesserae (arXiv:2508.04953)
treat accelerator loss as a first-class scheduling event; to *prove* the
recovery paths in tier-1 we need failures that are injectable, seeded,
and bit-reproducible — not `kill -9` roulette.

A `FaultPlan` maps *site names* (dotted paths baked into the production
code: `solver.dispatch.pallas`, `raft.apply`, `heartbeat.invalidate`,
...) to specs. Each spec has a mode:

  raise        fire on every call
  delay        sleep `delay_ms` then continue (slow disk, busy device)
  nth_call     fire on every n-th call at that site (1-based)
  after        fire on EVERY call from the n-th onward (1-based) — the
               partition shape: a link that works N-1 times and then
               stays dead until the plan is cleared/healed
  probability  fire with probability `p` from a PER-SITE seeded RNG —
               same seed => same fire pattern over the site's call
               sequence, independent of other sites' traffic
  torn         BYTES sites only (`faults.mangle(site, data)`): from the
               n-th call onward (default 1), raise TornWriteError
               carrying a seeded PREFIX of the payload — the write site
               writes the prefix, then propagates (power loss mid-write)
  corrupt      BYTES sites only: from the n-th call onward, return the
               payload with ONE seeded bit flipped and continue — the
               write "succeeds" but what reached the platter is damaged
               (silent media corruption, detected later by CRC)

plus common knobs: `times` caps total fires (-1 = unlimited; `times: 1`
is a one-shot), and `exc` picks the raised type (`fault` -> FaultError,
`timeout` -> TimeoutError, `oom` -> MemoryError, `runtime` ->
RuntimeError, `device_lost` -> DeviceLostError, an XlaRuntimeError-shaped
accelerator loss — the default at `device.lost.d<N>` sites) so a site
can simulate its real failure shape.

Install via the test API (`faults.install({...})`) or the environment:

    NOMAD_FAULTS='{"solver.dispatch.pallas": {"mode": "raise"},
                   "raft.apply": {"mode": "nth_call", "n": 3, "times": 2}}'

The env form crosses process boundaries, so the multi-process e2e tier
can chaos a real agent. A site key ending in `.*` prefix-matches
(`solver.dispatch.*` faults every tier); exact keys win over wildcards.

Call sites invoke `faults.fire("<site>")`, a no-op costing one module
attribute read when no plan is installed — the production hot path pays
nothing. Fired/observed counts per site are queryable (`faults.fired`)
and mirrored into metrics (`nomad.faults.fired.<site>`), so tests and
the bench can assert the chaos actually happened.

Site catalog: docs/FAULT_INJECTION.md.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Optional

from .metrics import metrics


class FaultError(RuntimeError):
    """An injected failure. Solver dispatch sites treat it exactly like a
    device-tier error (XlaRuntimeError), so the degradation ladder can be
    exercised without a sick TPU."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site}")
        self.site = site


class TornWriteError(FaultError):
    """A `torn`-mode fire at a bytes site (ISSUE 13): `.prefix` is the
    seeded prefix of the payload that "reached the disk" before the
    simulated power loss. The write site's contract: write the prefix,
    flush it, then let this propagate as the crash."""

    def __init__(self, site: str, prefix: bytes):
        super().__init__(site)
        self.prefix = prefix


# The device-loss error type (ISSUE 14): XlaRuntimeError-shaped — it
# subclasses the REAL jax runtime error where available, so every
# `except backend.device_error_types()` seam catches it exactly like a
# genuine torn-pod/preempted-slice error, while also deriving FaultError
# so environments without jax internals still demote. Built lazily: the
# class base depends on jax internals whose import must not be paid by
# processes that never dispatch (agents, the CLI).
_DEVICE_LOST_CLS = None


def device_lost_error_type():
    """The DeviceLostError class (lazily built, see above)."""
    global _DEVICE_LOST_CLS
    if _DEVICE_LOST_CLS is None:
        try:
            from jax._src.lib import xla_client
            base = xla_client.XlaRuntimeError
        except Exception:   # noqa: BLE001 — internal layout, best-effort
            base = None
        if base is None or issubclass(FaultError, base):
            # no jax internals (or XlaRuntimeError degenerates to a
            # FaultError ancestor): FaultError alone — adding the
            # ancestor again would make the MRO inconsistent
            bases: tuple = (FaultError,)
        else:
            bases = (base, FaultError)

        class DeviceLostError(*bases):
            """An injected device loss (`device.lost.d<N>` sites): the
            accelerator behind `device_id` is gone — preempted slice,
            torn pod, runtime reset. Dispatch seams classify this as
            device-loss (backend.classify_device_error) and trigger a
            mesh generation rebuild instead of a transient demotion."""

            def __init__(self, site: str):
                did = -1
                tail = site.rsplit(".", 1)[-1]
                if tail.startswith("d") and tail[1:].isdigit():
                    did = int(tail[1:])
                RuntimeError.__init__(
                    self, f"INTERNAL: injected DEVICE_LOST at {site}: "
                    f"device d{did} handle is invalid")
                self.site = site
                self.device_id = did

        _DEVICE_LOST_CLS = DeviceLostError
    return _DEVICE_LOST_CLS


_EXC_TYPES = {
    "fault": FaultError,
    "timeout": TimeoutError,
    "oom": MemoryError,
    "runtime": RuntimeError,
    "device_lost": None,        # resolved lazily (device_lost_error_type)
}

_MODES = ("raise", "delay", "nth_call", "after", "probability",
          "torn", "corrupt")
# modes that only act on byte payloads (via mangle()); a plain fire()
# at a site matched by one of these is counted but never raises — the
# site has no bytes to tear/corrupt
_BYTES_MODES = ("torn", "corrupt")


class FaultSpec:
    """One site pattern's behavior + its call/fire bookkeeping."""

    __slots__ = ("pattern", "mode", "n", "p", "seed", "delay_ms", "times",
                 "exc", "calls", "fires", "_rng")

    def __init__(self, pattern: str, mode: str, n: int = 1, p: float = 1.0,
                 seed: int = 0, delay_ms: float = 0.0, times: int = -1,
                 exc: str = "fault"):
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r} (one of {_MODES})")
        if exc not in _EXC_TYPES:
            raise ValueError(f"unknown fault exc {exc!r} "
                             f"(one of {tuple(_EXC_TYPES)})")
        if mode in ("nth_call", "after", "torn", "corrupt") and n < 1:
            raise ValueError(f"{mode} requires n >= 1")
        if exc == "fault" and pattern.startswith("device.lost."):
            # device.lost.d<N> sites default to the XlaRuntimeError-shaped
            # loss (a plain FaultError there would classify as transient
            # and never exercise the rebuild path the site exists for)
            exc = "device_lost"
        self.pattern = pattern
        self.mode = mode
        self.n = int(n)
        self.p = float(p)
        self.seed = int(seed)
        self.delay_ms = float(delay_ms)
        self.times = int(times)
        self.exc = exc
        self.calls = 0
        self.fires = 0
        # per-spec stream seeded off (seed, pattern): a site's fire
        # pattern is a pure function of its own call sequence — other
        # sites' traffic can't perturb it (the determinism contract)
        self._rng = random.Random(f"{self.seed}:{pattern}")

    def should_fire(self) -> bool:
        """Caller already counted the call (self.calls is 1-based)."""
        if 0 <= self.times <= self.fires:
            return False
        if self.mode in ("raise", "delay"):
            return True
        if self.mode == "nth_call":
            return self.calls % self.n == 0
        if self.mode in ("after", "torn", "corrupt"):
            # torn/corrupt compose with `n` + `times` so a crash-point
            # fuzzer can say "tear exactly the k-th write at this site"
            return self.calls >= self.n
        return self._rng.random() < self.p          # probability

    def raise_now(self, site: str) -> None:
        if self.exc == "device_lost":
            raise device_lost_error_type()(site)
        exc_type = _EXC_TYPES[self.exc]
        if exc_type is FaultError:
            raise FaultError(site)
        raise exc_type(f"injected fault at {site}")

    def mangle_now(self, site: str, data: bytes) -> bytes:
        """Apply a fired bytes-mode spec to a payload (under the plan
        lock). `torn` raises with the seeded prefix; `corrupt` returns
        the payload with one seeded bit flipped."""
        if not data:
            if self.mode == "torn":
                raise TornWriteError(site, b"")
            return data
        if self.mode == "torn":
            k = self._rng.randrange(len(data))
            raise TornWriteError(site, data[:k])
        pos = self._rng.randrange(len(data))
        bit = 1 << self._rng.randrange(8)
        return data[:pos] + bytes([data[pos] ^ bit]) + data[pos + 1:]


class FaultPlan:
    """A set of FaultSpecs + thread-safe fire bookkeeping."""

    def __init__(self, specs: dict):
        self._lock = threading.Lock()
        self.specs: dict[str, FaultSpec] = {}
        # site -> (calls, fires) for sites observed but not matched, so
        # tests can assert a site is *wired* without faulting it
        self.observed: dict[str, int] = {}
        for pattern, raw in (specs or {}).items():
            if isinstance(raw, FaultSpec):
                spec = raw
            else:
                spec = FaultSpec(pattern, **{str(k): v
                                             for k, v in dict(raw).items()})
            self.specs[pattern] = spec

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError("NOMAD_FAULTS must be a JSON object "
                             "{site: spec}")
        return cls(doc)

    def _match(self, site: str) -> Optional[FaultSpec]:
        spec = self.specs.get(site)
        if spec is not None:
            return spec
        for pattern, cand in self.specs.items():
            if pattern.endswith(".*") and site.startswith(pattern[:-1]):
                # instantiate a per-site spec on first wildcard match:
                # sharing one RNG/counter across sites would make the
                # fire pattern thread-interleaving-dependent, breaking
                # the per-site determinism contract. `times` therefore
                # caps each concrete site independently.
                child = FaultSpec(site, cand.mode, n=cand.n, p=cand.p,
                                  seed=cand.seed, delay_ms=cand.delay_ms,
                                  times=cand.times, exc=cand.exc)
                self.specs[site] = child
                return child
        return None

    def fire(self, site: str) -> None:
        delay_s = 0.0
        spec = None
        with self._lock:
            self.observed[site] = self.observed.get(site, 0) + 1
            spec = self._match(site)
            if spec is None:
                return
            if spec.mode in _BYTES_MODES:
                # bytes-only modes act through mangle(); a plain fire()
                # at the same site is observed but can't tear anything
                return
            spec.calls += 1
            if not spec.should_fire():
                return
            spec.fires += 1
            metrics.incr("nomad.faults.fired")
            metrics.incr(f"nomad.faults.fired.{site}")
            if spec.mode == "delay":
                delay_s = spec.delay_ms / 1000.0
        if spec.mode == "delay":
            time.sleep(delay_s)                     # outside the lock
            return
        spec.raise_now(site)

    def mangle(self, site: str, data: bytes) -> bytes:
        """Bytes-site injection point (ISSUE 13): returns the payload to
        actually write. `corrupt` returns a seeded one-bit-flipped copy;
        `torn` raises TornWriteError carrying the seeded prefix; every
        NON-bytes mode behaves exactly like fire() here, so one spec can
        target a write site whichever way the test needs."""
        delay_s = 0.0
        spec = None
        with self._lock:
            self.observed[site] = self.observed.get(site, 0) + 1
            spec = self._match(site)
            if spec is None:
                return data
            spec.calls += 1
            if not spec.should_fire():
                return data
            spec.fires += 1
            metrics.incr("nomad.faults.fired")
            metrics.incr(f"nomad.faults.fired.{site}")
            if spec.mode in _BYTES_MODES:
                return spec.mangle_now(site, data)
            if spec.mode == "delay":
                delay_s = spec.delay_ms / 1000.0
        if spec.mode == "delay":
            time.sleep(delay_s)                     # outside the lock
            return data
        spec.raise_now(site)

    def fired(self, site_or_pattern: str) -> int:
        with self._lock:
            spec = self.specs.get(site_or_pattern) \
                or self._match(site_or_pattern)
            return spec.fires if spec else 0

    def calls(self, site: str) -> int:
        with self._lock:
            return self.observed.get(site, 0)


# ------------------------------------------------------------ module API

_plan: Optional[FaultPlan] = None


def install(plan) -> FaultPlan:
    """Install a plan (FaultPlan, dict, or JSON string). Test API twin of
    the NOMAD_FAULTS env install."""
    global _plan
    if isinstance(plan, str):
        plan = FaultPlan.from_json(plan)
    elif isinstance(plan, dict):
        plan = FaultPlan(plan)
    _plan = plan
    return plan


def install_from_env() -> Optional[FaultPlan]:
    text = os.environ.get("NOMAD_FAULTS", "")
    if not text:
        return None
    return install(FaultPlan.from_json(text))


def clear() -> None:
    global _plan
    _plan = None


def active() -> Optional[FaultPlan]:
    return _plan


def fire(site: str) -> None:
    """The injection point. No plan installed => one attribute read."""
    plan = _plan
    if plan is None:
        return
    plan.fire(site)


def mangle(site: str, data: bytes) -> bytes:
    """Bytes-site injection point: the caller writes whatever comes
    back. No plan installed => one attribute read and the same bytes.
    A `torn` spec raises TornWriteError — the site writes `.prefix`,
    flushes, and re-raises (the simulated power loss)."""
    plan = _plan
    if plan is None:
        return data
    return plan.mangle(site, data)


def fired(site: str) -> int:
    plan = _plan
    return plan.fired(site) if plan else 0


# one env read at import: agent/e2e processes inherit NOMAD_FAULTS at
# spawn; in-process tests use install()/clear()
install_from_env()
