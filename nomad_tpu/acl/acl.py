"""ACL capability checking (ref acl/acl.go:43 ACL, NewACL).

An ACL merges one or more parsed policies into effective capability sets.
Namespace and host-volume rules support glob patterns; on overlap the most
specific matching pattern wins (ref acl.go findClosestMatchingGlob — highest
literal-prefix length, ties broken by fewer wildcards).
"""
from __future__ import annotations

import fnmatch
from typing import Iterable, Optional

from .policy import (
    HOST_VOLUME_DENY, NS_DENY, POLICY_DENY, POLICY_LIST, POLICY_READ,
    POLICY_WRITE, Policy,
)

_LEVEL = {"": 0, POLICY_LIST: 1, POLICY_READ: 2, POLICY_WRITE: 3,
          POLICY_DENY: -1}


def _merge_coarse(a: str, b: str) -> str:
    """deny wins; otherwise the broader grant wins."""
    if POLICY_DENY in (a, b):
        return POLICY_DENY
    return a if _LEVEL.get(a, 0) >= _LEVEL.get(b, 0) else b


def _glob_specificity(pattern: str) -> tuple[int, int]:
    literal = len(pattern.split("*", 1)[0].split("?", 1)[0])
    wildcards = pattern.count("*") + pattern.count("?")
    return (literal, -wildcards)


class ACL:
    def __init__(self, management: bool = False,
                 policies: Iterable[Policy] = ()):
        self.management = management
        self._ns: dict[str, set[str]] = {}
        self._hv: dict[str, set[str]] = {}
        self.agent = ""
        self.node = ""
        self.operator = ""
        self.quota = ""
        self.plugin = ""
        for pol in policies:
            self._merge(pol)

    def _merge(self, pol: Policy) -> None:
        for np in pol.namespaces:
            caps = self._ns.setdefault(np.name, set())
            if NS_DENY in np.capabilities:
                caps.clear()
                caps.add(NS_DENY)
            elif NS_DENY not in caps:
                caps.update(np.capabilities)
        for hv in pol.host_volumes:
            caps = self._hv.setdefault(hv.name, set())
            if HOST_VOLUME_DENY in hv.capabilities:
                caps.clear()
                caps.add(HOST_VOLUME_DENY)
            elif HOST_VOLUME_DENY not in caps:
                caps.update(hv.capabilities)
        self.agent = _merge_coarse(self.agent, pol.agent)
        self.node = _merge_coarse(self.node, pol.node)
        self.operator = _merge_coarse(self.operator, pol.operator)
        self.quota = _merge_coarse(self.quota, pol.quota)
        self.plugin = _merge_coarse(self.plugin, pol.plugin)

    # -------------------------------------------------------------- lookup

    def _match(self, table: dict[str, set[str]], name: str
               ) -> Optional[set[str]]:
        if name in table:
            return table[name]
        best, best_spec = None, None
        for pattern, caps in table.items():
            if ("*" in pattern or "?" in pattern) and \
                    fnmatch.fnmatchcase(name, pattern):
                spec = _glob_specificity(pattern)
                if best_spec is None or spec > best_spec:
                    best, best_spec = caps, spec
        return best

    # -------------------------------------------------------------- checks

    def allow_namespace_operation(self, namespace: str, cap: str) -> bool:
        """ref acl.go AllowNamespaceOperation"""
        if self.management:
            return True
        caps = self._match(self._ns, namespace or "default")
        return bool(caps) and NS_DENY not in caps and cap in caps

    def allow_namespace(self, namespace: str) -> bool:
        """Any capability at all (ref acl.go AllowNamespace)."""
        if self.management:
            return True
        caps = self._match(self._ns, namespace or "default")
        return bool(caps) and NS_DENY not in caps

    def allow_host_volume_operation(self, volume: str, cap: str) -> bool:
        if self.management:
            return True
        caps = self._match(self._hv, volume)
        return bool(caps) and HOST_VOLUME_DENY not in caps and cap in caps

    def _coarse_allows(self, disp: str, write: bool) -> bool:
        if self.management:
            return True
        if disp == POLICY_DENY:
            return False
        if write:
            return disp == POLICY_WRITE
        return disp in (POLICY_READ, POLICY_WRITE)

    def allow_node_read(self) -> bool:
        return self._coarse_allows(self.node, write=False)

    def allow_node_write(self) -> bool:
        return self._coarse_allows(self.node, write=True)

    def allow_agent_read(self) -> bool:
        return self._coarse_allows(self.agent, write=False)

    def allow_agent_write(self) -> bool:
        return self._coarse_allows(self.agent, write=True)

    def allow_operator_read(self) -> bool:
        return self._coarse_allows(self.operator, write=False)

    def allow_operator_write(self) -> bool:
        return self._coarse_allows(self.operator, write=True)

    def allow_quota_read(self) -> bool:
        return self._coarse_allows(self.quota, write=False)

    def allow_quota_write(self) -> bool:
        return self._coarse_allows(self.quota, write=True)

    def allow_plugin_read(self) -> bool:
        return self._coarse_allows(self.plugin, write=False)

    def allow_plugin_list(self) -> bool:
        # list is a plugin-only disposition weaker than read
        # (ref acl/acl.go AllowPluginList)
        if self.management:
            return True
        return self.plugin == POLICY_LIST or \
            self._coarse_allows(self.plugin, write=False)

    def is_management(self) -> bool:
        return self.management


MANAGEMENT_ACL = ACL(management=True)


def parse_acl(policy_sources: Iterable[str]) -> ACL:
    from .policy import parse_policy
    return ACL(policies=[parse_policy(src) for src in policy_sources])
