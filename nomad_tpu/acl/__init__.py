"""ACL system: policy language, capability sets, token resolution
(ref acl/acl.go, acl/policy.go)."""
from .acl import ACL, MANAGEMENT_ACL, parse_acl
from .policy import (
    HostVolumePolicy, NamespacePolicy, Policy, PolicyParseError,
    expand_namespace_policy, parse_policy,
    NS_ALLOC_EXEC, NS_ALLOC_LIFECYCLE, NS_CSI_LIST_VOLUME,
    NS_CSI_MOUNT_VOLUME, NS_CSI_READ_VOLUME, NS_CSI_REGISTER_PLUGIN,
    NS_CSI_WRITE_VOLUME, NS_DENY, NS_DISPATCH_JOB,
    NS_LIST_JOBS, NS_LIST_SCALING_POLICIES, NS_PARSE_JOB, NS_READ_FS,
    NS_READ_JOB, NS_READ_JOB_SCALING, NS_READ_LOGS, NS_READ_SCALING_POLICY,
    NS_SCALE_JOB, NS_SUBMIT_JOB,
)

__all__ = [
    "ACL", "MANAGEMENT_ACL", "parse_acl", "parse_policy", "Policy",
    "NamespacePolicy", "HostVolumePolicy", "PolicyParseError",
    "expand_namespace_policy",
    "NS_ALLOC_EXEC", "NS_ALLOC_LIFECYCLE", "NS_CSI_LIST_VOLUME",
    "NS_CSI_MOUNT_VOLUME", "NS_CSI_READ_VOLUME", "NS_CSI_REGISTER_PLUGIN",
    "NS_CSI_WRITE_VOLUME", "NS_DENY", "NS_DISPATCH_JOB",
    "NS_LIST_JOBS", "NS_LIST_SCALING_POLICIES", "NS_PARSE_JOB", "NS_READ_FS",
    "NS_READ_JOB", "NS_READ_JOB_SCALING", "NS_READ_LOGS",
    "NS_READ_SCALING_POLICY", "NS_SCALE_JOB", "NS_SUBMIT_JOB",
]
