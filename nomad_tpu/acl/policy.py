"""ACL policy language (ref acl/policy.go:70 Parse + capability tables).

Policies are HCL documents:

    namespace "prod-*" {
      policy       = "read"
      capabilities = ["submit-job"]
    }
    node     { policy = "write" }
    agent    { policy = "read" }
    operator { policy = "write" }
    quota    { policy = "read" }
    plugin   { policy = "list" }
    host_volume "ssd-*" { policy = "write" }

Shorthand `policy =` dispositions expand to capability sets exactly as the
reference's expandNamespacePolicy does.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"
POLICY_SCALE = "scale"
POLICY_LIST = "list"

# namespace capabilities (ref acl/policy.go NamespaceCapability*)
NS_DENY = "deny"
NS_LIST_JOBS = "list-jobs"
NS_PARSE_JOB = "parse-job"
NS_READ_JOB = "read-job"
NS_SUBMIT_JOB = "submit-job"
NS_DISPATCH_JOB = "dispatch-job"
NS_READ_LOGS = "read-logs"
NS_READ_FS = "read-fs"
NS_ALLOC_EXEC = "alloc-exec"
NS_ALLOC_NODE_EXEC = "alloc-node-exec"
NS_ALLOC_LIFECYCLE = "alloc-lifecycle"
NS_SENTINEL_OVERRIDE = "sentinel-override"
NS_CSI_REGISTER_PLUGIN = "csi-register-plugin"
NS_CSI_WRITE_VOLUME = "csi-write-volume"
NS_CSI_READ_VOLUME = "csi-read-volume"
NS_CSI_LIST_VOLUME = "csi-list-volume"
NS_CSI_MOUNT_VOLUME = "csi-mount-volume"
NS_LIST_SCALING_POLICIES = "list-scaling-policies"
NS_READ_SCALING_POLICY = "read-scaling-policy"
NS_READ_JOB_SCALING = "read-job-scaling"
NS_SCALE_JOB = "scale-job"

_NS_READ_CAPS = [
    NS_LIST_JOBS, NS_PARSE_JOB, NS_READ_JOB, NS_CSI_LIST_VOLUME,
    NS_CSI_READ_VOLUME, NS_READ_JOB_SCALING, NS_LIST_SCALING_POLICIES,
    NS_READ_SCALING_POLICY,
]
_NS_WRITE_CAPS = _NS_READ_CAPS + [
    NS_SCALE_JOB, NS_SUBMIT_JOB, NS_DISPATCH_JOB, NS_READ_LOGS, NS_READ_FS,
    NS_ALLOC_EXEC, NS_ALLOC_LIFECYCLE, NS_CSI_WRITE_VOLUME,
    NS_CSI_MOUNT_VOLUME,
]
_NS_SCALE_CAPS = [NS_READ_JOB_SCALING, NS_LIST_SCALING_POLICIES,
                  NS_READ_SCALING_POLICY, NS_SCALE_JOB]

_ALL_NS_CAPS = set(_NS_WRITE_CAPS) | {NS_DENY, NS_SENTINEL_OVERRIDE,
                                      NS_CSI_REGISTER_PLUGIN,
                                      NS_ALLOC_NODE_EXEC}

HOST_VOLUME_MOUNT_READONLY = "mount-readonly"
HOST_VOLUME_MOUNT_READWRITE = "mount-readwrite"
HOST_VOLUME_DENY = "deny"


class PolicyParseError(Exception):
    pass


@dataclass
class NamespacePolicy:
    name: str = "default"
    policy: str = ""
    capabilities: list[str] = field(default_factory=list)


@dataclass
class HostVolumePolicy:
    name: str = ""
    policy: str = ""
    capabilities: list[str] = field(default_factory=list)


@dataclass
class Policy:
    namespaces: list[NamespacePolicy] = field(default_factory=list)
    host_volumes: list[HostVolumePolicy] = field(default_factory=list)
    agent: str = ""
    node: str = ""
    operator: str = ""
    quota: str = ""
    plugin: str = ""
    raw: str = ""


def expand_namespace_policy(policy: str) -> list[str]:
    """ref acl/policy.go expandNamespacePolicy"""
    if policy == POLICY_DENY:
        return [NS_DENY]
    if policy == POLICY_READ:
        return list(_NS_READ_CAPS)
    if policy == POLICY_WRITE:
        return list(_NS_WRITE_CAPS)
    if policy == POLICY_SCALE:
        return list(_NS_SCALE_CAPS)
    raise PolicyParseError(f"invalid namespace policy {policy!r}")


def expand_host_volume_policy(policy: str) -> list[str]:
    if policy == POLICY_DENY:
        return [HOST_VOLUME_DENY]
    if policy == POLICY_READ:
        return [HOST_VOLUME_MOUNT_READONLY]
    if policy == POLICY_WRITE:
        return [HOST_VOLUME_MOUNT_READONLY, HOST_VOLUME_MOUNT_READWRITE]
    raise PolicyParseError(f"invalid host_volume policy {policy!r}")


_COARSE = {POLICY_DENY, POLICY_READ, POLICY_WRITE}


def parse_policy(src: str) -> Policy:
    """Parse an HCL policy document (ref acl/policy.go:253 Parse)."""
    from ..jobspec.hcl import EvalContext, HCLError, Unknown, parse
    try:
        body = parse(src)
    except HCLError as e:
        raise PolicyParseError(str(e))
    ctx = EvalContext()
    pol = Policy(raw=src)

    def attrs_of(blk) -> dict:
        out = {}
        for name, attr in blk.body.attributes().items():
            try:
                out[name] = ctx.evaluate(attr.expr)
            except Unknown as e:
                raise PolicyParseError(f"unknown variable {e.root!r}")
        return out

    for blk in body.items:
        if not hasattr(blk, "type"):
            raise PolicyParseError("top-level attributes not allowed")
        a = attrs_of(blk)
        if blk.type == "namespace":
            name = blk.labels[0] if blk.labels else "default"
            np = NamespacePolicy(
                name=name, policy=a.get("policy", ""),
                capabilities=list(a.get("capabilities", []) or []))
            if np.policy:
                if np.policy not in (_COARSE | {POLICY_SCALE}):
                    raise PolicyParseError(
                        f"invalid namespace policy {np.policy!r}")
                np.capabilities = list(dict.fromkeys(
                    expand_namespace_policy(np.policy) + np.capabilities))
            bad = set(np.capabilities) - _ALL_NS_CAPS
            if bad:
                raise PolicyParseError(
                    f"invalid namespace capabilities {sorted(bad)}")
            pol.namespaces.append(np)
        elif blk.type == "host_volume":
            name = blk.labels[0] if blk.labels else ""
            hv = HostVolumePolicy(
                name=name, policy=a.get("policy", ""),
                capabilities=list(a.get("capabilities", []) or []))
            if hv.policy:
                if hv.policy not in _COARSE:
                    raise PolicyParseError(
                        f"invalid host_volume policy {hv.policy!r}")
                hv.capabilities = list(dict.fromkeys(
                    expand_host_volume_policy(hv.policy) + hv.capabilities))
            bad = set(hv.capabilities) - {HOST_VOLUME_MOUNT_READONLY,
                                          HOST_VOLUME_MOUNT_READWRITE,
                                          HOST_VOLUME_DENY}
            if bad:
                raise PolicyParseError(
                    f"invalid host_volume capabilities {sorted(bad)}")
            pol.host_volumes.append(hv)
        elif blk.type in ("agent", "node", "operator", "quota", "plugin"):
            disp = a.get("policy", "")
            allowed = _COARSE | ({POLICY_LIST} if blk.type == "plugin"
                                 else set())
            if disp not in allowed:
                raise PolicyParseError(
                    f"invalid {blk.type} policy {disp!r}")
            setattr(pol, blk.type, disp)
        else:
            raise PolicyParseError(f"unknown policy block {blk.type!r}")
    return pol
