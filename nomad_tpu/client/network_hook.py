"""Alloc network hook: bridge-mode network namespaces with port mapping
(behavioral ref client/allocrunner/network_hook.go +
networking_bridge_linux.go + the CNI bridge plugin conf it drives).

A task group with ``network { mode = "bridge" }`` gets its own network
namespace joined to a shared ``nomad`` bridge, an IP from the bridge
subnet, and DNAT rules mapping each reserved/dynamic host port to the
group's ``to`` port inside the namespace — so tasks bind container-side
ports while the scheduler keeps owning host ports.

All privileged operations run through a Commander so the manager is
fully testable without root: the default ExecCommander shells out to
``ip``/``iptables`` (and requires CAP_NET_ADMIN), while tests inject a
recording fake. On hosts without the tooling the hook degrades to
host-mode networking with a logged warning, mirroring the reference's
fingerprint-gated behavior (bridge networking only activates on nodes
that fingerprint the kernel support).
"""
from __future__ import annotations

import ipaddress
import shutil
import subprocess
import threading
from typing import Optional

BRIDGE_NAME = "nomad"                     # ref nomadBridgeName
# ref defaultNomadAllocSubnet (networking_bridge_linux.go)
BRIDGE_SUBNET = "172.26.64.0/20"
IPTABLES_CHAIN = "NOMAD-ADMIN"            # ref cniAdminChainName


class Commander:
    """Shell-out boundary (swap for a fake in tests)."""

    def run(self, *argv: str) -> str:
        raise NotImplementedError

    def available(self) -> bool:
        raise NotImplementedError


class ExecCommander(Commander):
    def run(self, *argv: str) -> str:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=10)
        if out.returncode != 0:
            raise RuntimeError(
                f"{' '.join(argv)}: rc={out.returncode}: "
                f"{out.stderr.strip()}")
        return out.stdout

    def available(self) -> bool:
        import os
        return bool(shutil.which("ip")) and bool(shutil.which("iptables")) \
            and os.geteuid() == 0


class BridgeNetworkManager:
    """Creates/destroys per-alloc namespaces on the shared nomad bridge.

    IP assignment is a simple in-process allocator over the bridge
    subnet (the reference delegates this to the CNI host-local IPAM
    plugin with the same subnet); .1 is the bridge gateway.
    """

    def __init__(self, commander: Optional[Commander] = None, logger=None):
        self.cmd = commander or ExecCommander()
        self.logger = logger or (lambda msg: None)
        self._lock = threading.Lock()
        self._bridge_ready = False
        net = ipaddress.ip_network(BRIDGE_SUBNET)
        self._gateway = str(net.network_address + 1)
        self._prefix_len = net.prefixlen
        self._ip_pool = iter(net.hosts())
        next(self._ip_pool)               # skip the gateway
        self._leases: dict[str, str] = {}   # alloc_id -> ip
        self._free_ips: list[str] = []      # recycled leases, LIFO

    # ------------------------------------------------------------- bridge
    def _ensure_bridge(self) -> None:
        """ref networking_bridge_linux.go ensureForwardingRules + the CNI
        bridge plugin's lazy bridge creation."""
        if self._bridge_ready:
            return
        try:
            self.cmd.run("ip", "link", "show", BRIDGE_NAME)
        except RuntimeError:
            self.cmd.run("ip", "link", "add", BRIDGE_NAME, "type", "bridge")
            self.cmd.run("ip", "addr", "add",
                         f"{self._gateway}/{self._prefix_len}",
                         "dev", BRIDGE_NAME)
        self.cmd.run("ip", "link", "set", BRIDGE_NAME, "up")
        # admin chain ensuring bridge traffic is forwarded (ref
        # ensureForwardingRules): `-C` probes for the jump rule and
        # exits non-zero when absent — that is the fresh-host case, so
        # insert it then
        try:
            self.cmd.run("iptables", "-N", IPTABLES_CHAIN)
        except RuntimeError:
            pass                          # chain exists
        try:
            self.cmd.run("iptables", "-C", "FORWARD", "-j", IPTABLES_CHAIN)
        except RuntimeError:
            self.cmd.run("iptables", "-I", "FORWARD", "-j", IPTABLES_CHAIN)
        self._bridge_ready = True

    # -------------------------------------------------------------- setup
    @staticmethod
    def netns_name(alloc_id: str) -> str:
        # full alloc id (ADVICE r4): netns names allow 255 chars, and an
        # 8-hex prefix collides across live allocs often enough that the
        # failure mode (cross-alloc teardown) is worth avoiding outright
        return f"nomad-{alloc_id}"

    def setup(self, alloc_id: str, ports: list[dict]) -> dict:
        """Create the alloc namespace; returns {"ip", "netns", "gateway"}.

        ports: [{"label", "value" (host), "to" (container)}] — one DNAT
        rule per mapped port (ref getPortMapping + the CNI portmap
        plugin).
        """
        ns = self.netns_name(alloc_id)
        # IFNAMSIZ caps interface names at 15 chars: "veth" + 11 id chars
        # (dashes stripped) is the most entropy that fits
        veth_host = f"veth{alloc_id.replace('-', '')[:11]}"
        veth_ns = "eth0"
        with self._lock:
            self._ensure_bridge()
            ip = self._leases.get(alloc_id)
            if ip is None:
                # recycled leases first so a long-lived client never
                # exhausts the subnet (the host-local IPAM plugin the
                # reference drives recycles the same way)
                ip = (self._free_ips.pop() if self._free_ips
                      else str(next(self._ip_pool)))
                self._leases[alloc_id] = ip
        try:
            self.cmd.run("ip", "netns", "add", ns)
            self.cmd.run("ip", "link", "add", veth_host, "type", "veth",
                         "peer", "name", veth_ns, "netns", ns)
            self.cmd.run("ip", "link", "set", veth_host, "master",
                         BRIDGE_NAME)
            self.cmd.run("ip", "link", "set", veth_host, "up")
            self.cmd.run("ip", "-n", ns, "addr", "add",
                         f"{ip}/{self._prefix_len}", "dev", veth_ns)
            self.cmd.run("ip", "-n", ns, "link", "set", veth_ns, "up")
            self.cmd.run("ip", "-n", ns, "link", "set", "lo", "up")
            self.cmd.run("ip", "-n", ns, "route", "add", "default", "via",
                         self._gateway)
            for p in ports:
                to = int(p.get("to") or p.get("value") or 0)
                host_port = int(p.get("value") or 0)
                if host_port <= 0 or to <= 0:
                    continue
                self.cmd.run(
                    "iptables", "-t", "nat", "-A", "PREROUTING",
                    "-p", "tcp", "--dport", str(host_port),
                    "-j", "DNAT", "--to-destination", f"{ip}:{to}",
                    "-m", "comment", "--comment", f"nomad-alloc-{alloc_id}")
        except RuntimeError:
            self.teardown(alloc_id, ports)
            raise
        return {"ip": ip, "netns": ns, "gateway": self._gateway}

    # ------------------------------------------------------------ teardown
    def teardown(self, alloc_id: str, ports: list[dict]) -> None:
        ns = self.netns_name(alloc_id)
        with self._lock:
            ip = self._leases.pop(alloc_id, None)
            if ip is not None:
                self._free_ips.append(ip)
        if ip is not None:
            for p in ports or []:
                to = int(p.get("to") or p.get("value") or 0)
                host_port = int(p.get("value") or 0)
                if host_port <= 0 or to <= 0:
                    continue
                try:
                    self.cmd.run(
                        "iptables", "-t", "nat", "-D", "PREROUTING",
                        "-p", "tcp", "--dport", str(host_port),
                        "-j", "DNAT", "--to-destination", f"{ip}:{to}",
                        "-m", "comment", "--comment",
                        f"nomad-alloc-{alloc_id}")
                except RuntimeError:
                    pass
        else:
            # no lease (client restarted since setup): find this alloc's
            # rules by their comment tag in iptables-save output and
            # delete each by exact spec. Rules stamped by a pre-upgrade
            # client carry the legacy short tag, so match both formats
            # (quoted exactly — a bare prefix match could hit another
            # alloc sharing the 8-char id prefix)
            try:
                saved = self.cmd.run("iptables-save", "-t", "nat")
            except RuntimeError:
                saved = ""
            tags = (f'"nomad-alloc-{alloc_id}"',
                    f'"nomad-alloc-{alloc_id[:8]}"')
            for line in (saved or "").splitlines():
                if line.startswith("-A ") and any(t in line for t in tags):
                    # iptables-save quotes comment values; the live rule
                    # has no quotes, so strip them or -D never matches
                    spec = [tok.strip('"') for tok in line.split()[1:]]
                    try:
                        self.cmd.run("iptables", "-t", "nat", "-D", *spec)
                    except RuntimeError:
                        pass
        # also reap the legacy short-named namespace a pre-upgrade client
        # may have created for this alloc
        for name in {ns, f"nomad-{alloc_id[:8]}"}:
            try:
                self.cmd.run("ip", "netns", "delete", name)
            except RuntimeError:
                pass                      # already gone (idempotent stop)


class CNINetworkManager:
    """Execute a CNI plugin chain from a .conflist (ref
    client/allocrunner/networking_cni.go + the CNI spec's exec protocol):
    a group with ``network { mode = "cni/<name>" }`` runs every plugin in
    the named conflist with CNI_COMMAND=ADD at alloc start and DEL (in
    reverse order) at stop. Plugin invocation goes through an injectable
    runner so the chain is testable without CNI binaries; the default
    runner executes ``<cni_bin_dir>/<type>`` with the conf on stdin, per
    the spec."""

    def __init__(self, config_dir: str = "/opt/cni/config",
                 bin_dir: str = "/opt/cni/bin", runner=None, logger=None,
                 netns=None):
        self.config_dir = config_dir
        self.bin_dir = bin_dir
        self.logger = logger or (lambda msg: None)
        self.runner = runner or self._exec_runner
        # netns lifecycle is NOMAD's job, not the plugins' (ref
        # networking_bridge_linux.go: the runtime creates the sandbox,
        # CNI wires it). Injectable alongside the runner for tests; when
        # a custom runner is supplied without a netns fn, default to
        # no-op (the fake plugin world has no kernel namespaces).
        if netns is not None:
            self.netns = netns
        elif runner is not None:
            self.netns = lambda action, name: None
        else:
            self.netns = self._exec_netns
        # (alloc, net) -> (ADD result, conflist used) — DEL must run the
        # SAME config ADD ran even if the file was removed meanwhile
        self._results: dict[tuple, tuple] = {}

    @staticmethod
    def _exec_netns(action: str, name: str) -> None:
        out = subprocess.run(["ip", "netns", action, name],
                             capture_output=True, text=True, timeout=10)
        if out.returncode != 0 and action == "add":
            raise RuntimeError(f"ip netns {action} {name}: "
                               f"{out.stderr.strip()}")

    def _exec_runner(self, plugin_type: str, env: dict,
                     conf_json: str) -> str:
        import os
        binary = f"{self.bin_dir}/{plugin_type}"
        out = subprocess.run([binary], input=conf_json, env={
            **os.environ, **env}, capture_output=True, text=True,
            timeout=30)
        if out.returncode != 0:
            raise RuntimeError(f"CNI {plugin_type} "
                               f"{env.get('CNI_COMMAND')}: "
                               f"{out.stderr.strip() or out.stdout.strip()}")
        return out.stdout

    def available(self, net_name: str) -> bool:
        return self._load_conflist(net_name) is not None

    def _load_conflist(self, net_name: str):
        import json
        import os
        try:
            names = sorted(os.listdir(self.config_dir))
        except OSError:
            return None
        for fn in names:
            if not (fn.endswith(".conflist") or fn.endswith(".conf")):
                continue
            try:
                with open(os.path.join(self.config_dir, fn)) as f:
                    conf = json.load(f)
            except (OSError, ValueError):
                continue
            if conf.get("name") == net_name:
                if "plugins" not in conf:       # bare .conf -> one-plugin
                    conf = {"name": conf.get("name"),
                            "cniVersion": conf.get("cniVersion", "1.0.0"),
                            "plugins": [conf]}
                return conf
        return None

    def _env(self, command: str, alloc_id: str) -> dict:
        return {
            "CNI_COMMAND": command,
            "CNI_CONTAINERID": alloc_id,
            "CNI_NETNS": f"/var/run/netns/nomad-{alloc_id}",
            "CNI_IFNAME": "eth0",
            "CNI_PATH": self.bin_dir,
        }

    @staticmethod
    def _port_mappings(ports: list[dict]) -> list[dict]:
        return [{"hostPort": p.get("value"),
                 "containerPort": p.get("to") or p.get("value"),
                 "protocol": "tcp"} for p in ports]

    def _plugin_conf(self, plugin: dict, conf: dict, prev,
                     ports: list[dict]) -> dict:
        """Per-plugin stdin config: name/version injection, prevResult
        chaining, and capability args delivered as runtimeConfig — the
        ONLY channel real plugins read them from (libcni injects
        runtimeConfig for each capability the plugin declares; ref
        getPortMapping + the CNI conventions doc)."""
        pconf = {**plugin, "name": conf["name"],
                 "cniVersion": conf.get("cniVersion", "1.0.0")}
        if prev is not None:
            pconf["prevResult"] = prev
        if (plugin.get("capabilities") or {}).get("portMappings"):
            pconf["runtimeConfig"] = {
                "portMappings": self._port_mappings(ports)}
        return pconf

    def setup(self, alloc_id: str, net_name: str,
              ports: list[dict]):
        """Run the ADD chain; returns the netns status dict, or None when
        the named network has no conflist (caller falls back to host
        networking — returning None instead of raising closes the
        available()/setup() TOCTOU window)."""
        import json
        conf = self._load_conflist(net_name)
        if conf is None:
            return None
        ns = f"nomad-{alloc_id}"
        self.netns("add", ns)
        env = self._env("ADD", alloc_id)
        prev = None
        added: list = []
        try:
            for plugin in conf["plugins"]:
                pconf = self._plugin_conf(plugin, conf, prev, ports)
                out = self.runner(plugin.get("type", ""), env,
                                  json.dumps(pconf))
                added.append(plugin)
                try:
                    prev = json.loads(out) if out.strip() else prev
                except ValueError:
                    pass                 # plugins may emit empty output
        except Exception:
            # mid-chain failure: unwind what DID run (reverse DEL) and
            # drop the netns, or every scheduler retry leaks an IPAM
            # lease + namespace
            del_env = self._env("DEL", alloc_id)
            for plugin in reversed(added):
                try:
                    self.runner(plugin.get("type", ""), del_env,
                                json.dumps(self._plugin_conf(
                                    plugin, conf, prev, ports)))
                except Exception as e:  # noqa: BLE001
                    self.logger(f"CNI rollback {plugin.get('type')}: "
                                f"{e!r}")
            try:
                self.netns("delete", ns)
            # unwind path: the ORIGINAL setup error re-raises below and
            # carries the diagnosis; a secondary netns-delete failure
            # must not mask it
            except Exception:  # nomadlint: disable=EXC001 — rollback
                pass
            raise
        result = prev or {}
        ips = result.get("ips") or []
        status = {"mode": f"cni/{net_name}", "netns": ns,
                  "ip": (ips[0].get("address", "").split("/")[0]
                         if ips else ""),
                  "result": result}
        self._results[(alloc_id, net_name)] = (result, conf)
        return status

    def teardown(self, alloc_id: str, net_name: str,
                 ports: list[dict]) -> None:
        import json
        cached = self._results.pop((alloc_id, net_name), None)
        if cached is not None:
            prev, conf = cached
        else:
            # client restarted since ADD: fall back to the on-disk conf
            prev, conf = None, self._load_conflist(net_name)
        ns = f"nomad-{alloc_id}"
        if conf is not None:
            env = self._env("DEL", alloc_id)
            # DEL runs the chain in REVERSE (CNI spec §4), with the SAME
            # config ADD used even if the file changed/vanished meanwhile
            for plugin in reversed(conf["plugins"]):
                try:
                    self.runner(plugin.get("type", ""), env,
                                json.dumps(self._plugin_conf(
                                    plugin, conf, prev, ports)))
                except Exception as e:  # noqa: BLE001 — keep deleting
                    self.logger(f"CNI DEL {plugin.get('type')}: {e!r}")
        try:
            self.netns("delete", ns)
        except Exception as e:          # noqa: BLE001 — already gone
            self.logger(f"CNI netns delete {ns}: {e!r}")


class NetworkHook:
    """The alloc-runner-facing hook (ref network_hook.go): no-ops unless
    the group requests bridge or cni/<name> mode AND the host supports
    it."""

    def __init__(self, manager: Optional[BridgeNetworkManager] = None,
                 logger=None, cni: Optional[CNINetworkManager] = None):
        self.logger = logger or (lambda msg: None)
        self.manager = manager or BridgeNetworkManager(logger=self.logger)
        self.cni = cni or CNINetworkManager(logger=self.logger)
        self.status: dict[str, dict] = {}    # alloc_id -> netns status

    @staticmethod
    def _bridge_requested(tg) -> bool:
        return bool(tg and tg.networks
                    and tg.networks[0].mode == "bridge")

    @staticmethod
    def _alloc_ports(alloc) -> list[dict]:
        res = alloc.allocated_resources
        if res is None or res.shared is None:
            return []
        return [dict(p) for p in (res.shared.ports or [])]

    @staticmethod
    def _cni_net(tg) -> str:
        mode = (tg.networks[0].mode if tg and tg.networks else "") or ""
        return mode[4:] if mode.startswith("cni/") else ""

    def prerun(self, alloc, tg) -> Optional[dict]:
        net = self._cni_net(tg)
        if net:
            st = self.cni.setup(alloc.id, net, self._alloc_ports(alloc))
            if st is None:
                self.logger(
                    f"network_hook: cni/{net} requested by alloc "
                    f"{alloc.id[:8]} but no conflist found; using host "
                    f"networking")
                return None
            self.status[alloc.id] = st
            return st
        if not self._bridge_requested(tg):
            return None
        if not self.manager.cmd.available():
            # degrade to host networking, as the reference does on nodes
            # whose fingerprint lacks bridge support
            self.logger(
                f"network_hook: bridge mode requested by alloc "
                f"{alloc.id[:8]} but host tooling unavailable; using "
                f"host networking")
            return None
        st = self.manager.setup(alloc.id, self._alloc_ports(alloc))
        self.status[alloc.id] = st
        return st

    def postrun(self, alloc, tg) -> None:
        net = self._cni_net(tg)
        if net:
            self.cni.teardown(alloc.id, net, self._alloc_ports(alloc))
            self.status.pop(alloc.id, None)
            return
        if alloc.id not in self.status:
            # a bridge alloc restored after a client restart has no
            # in-memory status (restore never re-runs prerun) — still
            # tear down the namespace so it isn't orphaned on the host;
            # teardown is idempotent when nothing exists
            if not (self._bridge_requested(tg)
                    and self.manager.cmd.available()):
                return
        self.manager.teardown(alloc.id, self._alloc_ports(alloc))
        self.status.pop(alloc.id, None)

