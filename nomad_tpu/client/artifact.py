"""Artifact fetching for task prestart (ref
client/allocrunner/taskrunner/artifact_hook.go + the go-getter subset the
jobspec exposes: http/https/file sources, checksum verification, archive
unpacking).

A job declares artifacts per task:

    artifact { source = "https://example.com/tool.tar.gz"
               destination = "local/bin"
               options { checksum = "sha256:abc..." } }

The fetcher downloads (or copies) the source into the task directory,
verifies any declared checksum, and unpacks recognized archives unless
`options.archive = "false"` — matching go-getter's default-unpack
behavior the reference relies on.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile


class ArtifactError(Exception):
    pass


_ARCHIVE_EXTS = (".tar.gz", ".tgz", ".tar.bz2", ".tbz2", ".tar.xz",
                 ".txz", ".tar", ".zip")


def _confined(root: str, target: str) -> bool:
    """True when realpath(target) stays inside realpath(root) — the one
    sandbox rule for destinations, tar members/links and zip members
    (sibling-prefix dirs like root + '-evil' must not pass)."""
    root_real = os.path.realpath(root)
    target_real = os.path.realpath(target)
    return target_real == root_real or \
        target_real.startswith(root_real + os.sep)


def _verify_checksum(path: str, spec: str) -> None:
    """spec: '<algo>:<hexdigest>' (go-getter checksum option)."""
    try:
        algo, want = spec.split(":", 1)
    except ValueError:
        raise ArtifactError(f"malformed checksum {spec!r}")
    try:
        h = hashlib.new(algo.strip().lower())
    except ValueError:
        raise ArtifactError(f"unsupported checksum algorithm {algo!r}")
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    got = h.hexdigest()
    if got != want.strip().lower():
        raise ArtifactError(
            f"checksum mismatch: want {algo}:{want}, got {algo}:{got}")


def _is_archive(name: str) -> bool:
    return name.lower().endswith(_ARCHIVE_EXTS)


def _safe_extract_tar(tf: tarfile.TarFile, dest: str) -> None:
    for member in tf.getmembers():
        if not _confined(dest, os.path.join(dest, member.name)):
            raise ArtifactError(f"archive member escapes dest: {member.name}")
        if member.islnk() or member.issym():
            link = os.path.join(dest, os.path.dirname(member.name),
                                member.linkname)
            if not _confined(dest, link):
                raise ArtifactError(
                    f"archive link escapes dest: {member.name}")
    tf.extractall(dest, filter="data")


def _unpack(path: str, dest: str) -> None:
    name = path.lower()
    if name.endswith(".zip"):
        with zipfile.ZipFile(path) as zf:
            for member in zf.namelist():
                if not _confined(dest, os.path.join(dest, member)):
                    raise ArtifactError(
                        f"archive member escapes dest: {member}")
            zf.extractall(dest)
    else:
        mode = "r:*"
        with tarfile.open(path, mode) as tf:
            _safe_extract_tar(tf, dest)
    os.unlink(path)


def fetch_artifact(artifact, task_dir: str, timeout: float = 30.0) -> str:
    """Fetch one TaskArtifact into the task directory.

    Returns the destination directory. Raises ArtifactError on any
    failure (the caller turns that into a task setup failure, ref
    artifact_hook.go Prestart -> wrapped as a recoverable error).
    """
    source = artifact.getter_source
    if not source:
        raise ArtifactError("artifact has no source")
    opts = artifact.getter_options or {}
    dest_rel = artifact.relative_dest or "local/"
    # the destination is job-controlled: confine it to the task dir the
    # same way the fs endpoints do (client.py _fs_path) — absolute paths
    # and ../ traversal must not write outside the sandbox
    dest = os.path.realpath(
        os.path.join(task_dir, dest_rel.lstrip("/")))
    if not _confined(task_dir, dest):
        raise ArtifactError(
            f"artifact destination escapes the task dir: {dest_rel!r}")

    parsed = urllib.parse.urlparse(source)
    fname = os.path.basename(parsed.path or source) or "artifact"
    staging = os.path.join(dest, fname)

    try:
        os.makedirs(dest, exist_ok=True)
        if parsed.scheme in ("http", "https"):
            try:
                with urllib.request.urlopen(source, timeout=timeout) \
                        as resp, open(staging, "wb") as out:
                    shutil.copyfileobj(resp, out)
            except Exception as e:    # noqa: BLE001 - network/protocol
                raise ArtifactError(f"fetch {source!r} failed: {e}") from e
        elif parsed.scheme in ("", "file"):
            src_path = parsed.path if parsed.scheme == "file" else source
            if not os.path.exists(src_path):
                raise ArtifactError(f"artifact source not found: {src_path}")
            shutil.copy2(src_path, staging)
        else:
            raise ArtifactError(
                f"unsupported artifact scheme {parsed.scheme!r}")

        checksum = opts.get("checksum", "")
        if checksum:
            _verify_checksum(staging, checksum)

        unpack = _is_archive(fname) and \
            str(opts.get("archive", "true")).lower() != "false"
        if unpack:
            try:
                _unpack(staging, dest)
            except (tarfile.TarError, zipfile.BadZipFile) as e:
                raise ArtifactError(f"unpack {fname!r} failed: {e}") from e
        else:
            mode = opts.get("mode", "")
            if mode:
                try:
                    os.chmod(staging, int(mode, 8))
                except ValueError:
                    pass
    except OSError as e:
        # directory-as-source, dest path collisions, ENOSPC, stale
        # mounts ... all become recoverable setup failures — an escaped
        # OSError would kill the alloc-runner thread and strand the alloc
        raise ArtifactError(f"artifact io error: {e}") from e
    return dest
