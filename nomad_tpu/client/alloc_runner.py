"""AllocRunner: one allocation's state machine (ref
client/allocrunner/alloc_runner.go:299 Run, clientAlloc:653, Update:809,
Restore:417).

Runs the group's TaskRunners with lifecycle ordering (prestart -> main ->
poststop), rolls task states up into a client status, tracks deployment
health (min_healthy_time), and reacts to server-desired stops.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from ..structs import (
    Allocation, AllocDeploymentStatus, TaskState,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING, ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT,
    TASK_STATE_DEAD, TASK_STATE_RUNNING,
)
from .driver import Driver
from .task_runner import TaskRunner
from .taskenv import build_task_env


class AllocRunner:
    def __init__(self, client, alloc: Allocation):
        self.client = client
        self.alloc = alloc
        self._lock = threading.Lock()
        self.task_runners: dict[str, TaskRunner] = {}
        self.task_states: dict[str, TaskState] = {}
        self._thread: Optional[threading.Thread] = None
        self._destroyed = threading.Event()
        self._waiters_done = threading.Event()
        self._dirty = threading.Event()   # state changed, sync to server
        self.deployment_healthy_at: float = 0.0
        # set once a terminal client status was acked by the server —
        # gates local GC (client.gc_alloc)
        self.synced_terminal = False

        self.alloc_dir = os.path.join(client.alloc_dir_root, alloc.id)

    # ---------------------------------------------------------------- run

    def run(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"alloc-{self.alloc.id[:8]}")
        self._thread.start()

    def _run(self) -> None:
        try:
            self._run_impl()
        finally:
            # release any CSI claims/mounts whatever path we exited on
            # (ref csi_hook.go Postrun)
            self.client.csi_manager.unmount_all(self.alloc)

    def _run_impl(self) -> None:
        alloc = self.alloc
        if alloc.server_terminal_status():
            return
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            self._set_client_status(ALLOC_CLIENT_FAILED,
                                    "task group not found in job")
            return
        os.makedirs(self.alloc_dir, exist_ok=True)

        # previous-alloc wait + ephemeral disk migration (ref
        # client/allocwatcher; the migrate_hook in alloc_runner_hooks.go)
        if alloc.previous_allocation:
            from .alloc_watcher import PrevAllocWatcher
            try:
                PrevAllocWatcher(self.client, alloc,
                                 logger=self.client.logger).wait_and_migrate()
            except Exception as e:      # noqa: BLE001 — best-effort
                self.client.logger(f"allocwatcher: migrate failed: {e!r}")

        # CSI volumes: claim + stage + publish before any task starts
        # (ref client/allocrunner/csi_hook.go Prerun)
        csi_reqs = [r for r in tg.volumes.values() if r.type == "csi"]
        if csi_reqs:
            try:
                for req in csi_reqs:
                    self.client.csi_manager.mount_volume(alloc, req)
            except Exception as e:      # noqa: BLE001
                self._set_client_status(ALLOC_CLIENT_FAILED,
                                        f"CSI volume mount failed: {e}")
                return

        prestart = [t for t in tg.tasks if t.is_prestart()]
        main = [t for t in tg.tasks
                if t.lifecycle is None or (t.is_prestart() and t.lifecycle.sidecar)]
        poststart = [t for t in tg.tasks if t.is_poststart()]
        poststop = [t for t in tg.tasks if t.is_poststop()]

        # prestart (non-sidecar) must finish before main starts
        # (ref client/allocrunner/task_hook_coordinator.go)
        blockers = []
        for task in prestart:
            tr = self._make_runner(task)
            tr.start()
            if not task.lifecycle.sidecar:
                blockers.append(tr)
        for tr in blockers:
            tr.wait_done()
            if tr.state.failed:
                self._set_client_status(ALLOC_CLIENT_FAILED,
                                        "prestart task failed")
                self._run_poststop(poststop)
                return

        runners = []
        for task in main:
            if task.is_prestart():
                continue  # sidecars already started
            tr = self._make_runner(task)
            tr.start()
            runners.append(tr)
        for task in poststart:
            tr = self._make_runner(task)
            tr.start()
            runners.append(tr)

        for tr in runners:
            tr.wait_done()
        # main work done: stop prestart sidecars
        for task in prestart:
            if task.lifecycle.sidecar:
                tr = self.task_runners.get(task.name)
                if tr:
                    tr.kill("main tasks finished")
                    tr.wait_done(timeout=10)
        self._run_poststop(poststop)
        self._waiters_done.set()

    def _run_poststop(self, tasks) -> None:
        runners = []
        for task in tasks:
            tr = self._make_runner(task)
            tr.start()
            runners.append(tr)
        for tr in runners:
            tr.wait_done(timeout=60)

    def _make_runner(self, task) -> TaskRunner:
        driver = self.client.get_driver(task.driver)
        task_dir = os.path.join(self.alloc_dir, task.name)
        env = build_task_env(self.alloc, task, self.client.node, task_dir,
                             self.alloc_dir,
                             os.path.join(task_dir, "secrets"))
        # device hook: reserved device instances -> visibility env vars
        # (ref taskrunner/device_hook.go); a reservation failure fails the
        # task rather than launching it without its devices
        setup_error = ""
        tres = self.alloc.allocated_resources.tasks.get(task.name)
        for ad in (tres.devices if tres else []):
            try:
                res = self.client.device_manager.reserve(ad)
                env.update(res.envs)
            except ValueError as e:
                setup_error = f"device reservation failed: {e}"
                self.client.logger(setup_error)
        tr = TaskRunner(self.alloc, task, driver, task_dir, env,
                        self._on_task_state, setup_error=setup_error)
        with self._lock:
            self.task_runners[task.name] = tr
        return tr

    # --------------------------------------------------------------- state

    def _on_task_state(self, task_name: str, state: TaskState) -> None:
        """ref alloc_runner.go:486 handleTaskStateUpdates"""
        with self._lock:
            self.task_states[task_name] = state
            # a failed leader/main task takes the others down
            if state.state == TASK_STATE_DEAD and state.failed:
                for name, tr in self.task_runners.items():
                    if name != task_name and not tr.state.failed:
                        tr.kill("sibling task failed")
        self._dirty.set()
        self.client.alloc_state_updated(self)

    def client_alloc(self) -> Allocation:
        """Roll task states up into the alloc's client view
        (ref alloc_runner.go:653 clientAlloc)."""
        with self._lock:
            states = dict(self.task_states)
        a = self.alloc.copy()
        a.task_states = states
        if not states:
            a.client_status = ALLOC_CLIENT_PENDING
        else:
            any_failed = any(s.failed for s in states.values())
            all_dead = all(s.state == TASK_STATE_DEAD for s in states.values())
            any_running = any(s.state == TASK_STATE_RUNNING
                              for s in states.values())
            if all_dead:
                a.client_status = (ALLOC_CLIENT_FAILED if any_failed
                                   else ALLOC_CLIENT_COMPLETE)
            elif any_failed:
                a.client_status = ALLOC_CLIENT_FAILED
            elif any_running:
                a.client_status = ALLOC_CLIENT_RUNNING
            else:
                a.client_status = ALLOC_CLIENT_PENDING
        a.deployment_status = self._deployment_status(a)
        a.modify_time_unix = time.time()
        return a

    def _deployment_status(self, a: Allocation
                           ) -> Optional[AllocDeploymentStatus]:
        """Deployment health (ref client/allocrunner/health_hook.go +
        allochealth tracker): healthy once all tasks run for
        min_healthy_time; unhealthy on failure."""
        if not self.alloc.deployment_id:
            return self.alloc.deployment_status
        tg = (self.alloc.job.lookup_task_group(self.alloc.task_group)
              if self.alloc.job else None)
        update = tg.update if tg else None
        min_healthy = update.min_healthy_time_sec if update else 10.0
        prev = self.alloc.deployment_status
        canary = bool(prev and prev.canary)
        if a.client_status == ALLOC_CLIENT_FAILED:
            return AllocDeploymentStatus(healthy=False, canary=canary,
                                         timestamp_unix=time.time())
        states = a.task_states
        if states and all(s.state == TASK_STATE_RUNNING and not s.failed
                          for s in states.values()):
            started = max(s.started_at for s in states.values())
            if time.time() - started >= min_healthy:
                return AllocDeploymentStatus(healthy=True, canary=canary,
                                             timestamp_unix=time.time())
        if prev is not None and prev.healthy is not None:
            return prev
        return AllocDeploymentStatus(healthy=None, canary=canary)

    # -------------------------------------------------------------- update

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new alloc version (ref alloc_runner.go:809)."""
        old_desired = self.alloc.desired_status
        self.alloc = alloc
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT) \
           and old_desired not in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            self.stop()

    def stop(self) -> None:
        with self._lock:
            runners = list(self.task_runners.values())
        for tr in runners:
            tr.kill("alloc stopped by server")
        self._dirty.set()
        self.client.alloc_state_updated(self)

    def destroy(self) -> None:
        self.stop()
        self._destroyed.set()

    def signal(self, task_name: str, sig: str) -> None:
        """Signal one task, or every task when task_name is empty (ref
        client/allocrunner Signal)."""
        from ..structs import TASK_STATE_RUNNING
        with self._lock:
            runners = dict(self.task_runners)
        if task_name:
            tr = runners.get(task_name)
            if tr is None:
                raise ValueError(f"unknown task {task_name!r}")
            tr.signal(sig)
            return
        # all-task signal: act only on running tasks, and check eligibility
        # up front so we never partially apply then error
        eligible = [tr for tr in runners.values()
                    if tr.state.state == TASK_STATE_RUNNING]
        if not eligible:
            raise ValueError("allocation has no running tasks")
        for tr in eligible:
            tr.signal(sig)

    def restart_task(self, task_name: str = "") -> None:
        """Restart one task or the whole alloc (ref allocrunner Restart)."""
        with self._lock:
            runners = dict(self.task_runners)
        if task_name:
            tr = runners.get(task_name)
            if tr is None:
                raise ValueError(f"unknown task {task_name!r}")
            tr.restart()
            return
        eligible = [tr for tr in runners.values()
                    if not tr._done.is_set()]
        if not eligible:
            raise ValueError("allocation has no restartable tasks")
        for tr in eligible:
            tr.restart()

    def stats(self) -> dict:
        """Per-task + rolled-up resource usage (ref
        client/allocrunner AllocStats / structs.AllocResourceUsage)."""
        with self._lock:
            runners = dict(self.task_runners)
        tasks = {name: tr.stats() for name, tr in runners.items()}
        return {
            "ResourceUsage": {
                "MemoryStats": {"RSS": sum(
                    t.get("memory_rss_bytes", 0) for t in tasks.values())},
                "CpuStats": {"TotalTicks": sum(
                    t.get("cpu_total_ticks", 0.0) for t in tasks.values())},
            },
            "Tasks": {
                name: {"ResourceUsage": {
                    "MemoryStats": {"RSS": t.get("memory_rss_bytes", 0)},
                    "CpuStats": {
                        "TotalTicks": t.get("cpu_total_ticks", 0.0),
                        "Percent": t.get("cpu_percent", 0.0)},
                }} for name, t in tasks.items()
            },
            "Timestamp": time.time(),
        }

    def is_done(self) -> bool:
        with self._lock:
            states = dict(self.task_states)
        return bool(states) and all(s.state == TASK_STATE_DEAD
                                    for s in states.values())

    # ------------------------------------------------------------- restore

    def restore(self, handles: dict[str, dict]) -> None:
        """Reattach task runners to live tasks (ref alloc_runner.go:417)."""
        alloc = self.alloc
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            return
        from .driver import TaskHandle
        for task in tg.tasks:
            h = handles.get(task.name)
            if not h:
                continue
            tr = self._make_runner(task)
            handle = TaskHandle(**h)
            if not tr.restore(handle):
                # task died while client was down
                tr.state.state = TASK_STATE_DEAD
                tr.state.failed = True
                tr.state.finished_at = time.time()
                self._on_task_state(task.name, tr.state)

    def persistable_handles(self) -> dict[str, dict]:
        with self._lock:
            out = {}
            for name, tr in self.task_runners.items():
                if tr.handle is not None and \
                   tr.state.state == TASK_STATE_RUNNING:
                    out[name] = {
                        "task_id": tr.handle.task_id,
                        "driver": tr.handle.driver,
                        "pid": tr.handle.pid,
                        "config": tr.handle.config,
                        "started_at": tr.handle.started_at,
                    }
            return out
