"""AllocRunner: one allocation's state machine (ref
client/allocrunner/alloc_runner.go:299 Run, clientAlloc:653, Update:809,
Restore:417).

Runs the group's TaskRunners with lifecycle ordering (prestart -> main ->
poststop), rolls task states up into a client status, tracks deployment
health (min_healthy_time), and reacts to server-desired stops.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from ..metrics import record_swallowed_error
from ..structs import (
    Allocation, AllocDeploymentStatus, TaskState,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING, ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT,
    TASK_STATE_DEAD, TASK_STATE_RUNNING,
)
from .driver import Driver
from .task_runner import TaskRunner
from .taskenv import build_task_env


class AllocRunner:
    def __init__(self, client, alloc: Allocation):
        self.client = client
        self.alloc = alloc
        self._lock = threading.Lock()
        self.task_runners: dict[str, TaskRunner] = {}
        self._template_watchers: dict[str, object] = {}
        self.task_states: dict[str, TaskState] = {}
        self._thread: Optional[threading.Thread] = None
        self._destroyed = threading.Event()
        self._waiters_done = threading.Event()
        self._dirty = threading.Event()   # state changed, sync to server
        self.deployment_healthy_at: float = 0.0
        # set once a terminal client status was acked by the server —
        # gates local GC (client.gc_alloc)
        self.synced_terminal = False
        self._vault_tokens: dict[str, str] = {}      # task -> token
        self._services_registered = False
        self._check_runners: list = []
        # serializes the WHOLE service register/deregister lifecycle
        # (claim + RPC + check-runner spawn vs teardown) — a dedicated
        # lock so the hot-path _lock never waits on a service RPC
        self._services_lock = threading.Lock()
        self._services_closed = False
        # bridge-mode netns status ({"ip","netns","gateway"}) or None
        self.network_status: Optional[dict] = None

        self.alloc_dir = os.path.join(client.alloc_dir_root, alloc.id)

    # ---------------------------------------------------------------- run

    def run(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"alloc-{self.alloc.id[:8]}")
        self._thread.start()

    def _run(self) -> None:
        try:
            self._run_impl()
        finally:
            # postrun hooks, whatever path we exited on: CSI unmount
            # (csi_hook.go), network namespace teardown (network_hook.go
            # Postrun), service deregistration (the consul group services
            # hook), vault token revocation (vault_hook.go Stop)
            try:
                job = self.alloc.job
                tg = job.lookup_task_group(self.alloc.task_group) \
                    if job else None
                self.client.network_hook.postrun(self.alloc, tg)
            except Exception as e:      # noqa: BLE001 — best effort
                self.client.logger(f"network_hook: teardown: {e!r}")
            self.client.csi_manager.unmount_all(self.alloc)
            self._deregister_services()
            for token in self._vault_tokens.values():
                try:
                    self.client.rpc.vault_revoke_token(token)
                except Exception as e:  # noqa: BLE001 — keep revoking
                    # an unrevoked token outlives the alloc until TTL —
                    # that deserves a log line and a counter (EXC001)
                    record_swallowed_error("client.vault.revoke", e,
                                           self.client.logger)
            self._vault_tokens.clear()

    def _start_vault_renewal(self, task, start_token: str,
                             ttl_sec: float) -> None:
        """Half-TTL renewal loop; a failed renewal applies the task's vault
        change_mode (ref client/vaultclient token renewal +
        taskrunner/vault_hook.go watch loop)."""
        def renew_loop():
            token = start_token
            interval = max(1.0, ttl_sec / 2)
            while not self._destroyed.wait(interval):
                if self._vault_tokens.get(task.name) != token:
                    return   # replaced or revoked
                try:
                    self.client.rpc.vault_renew_token(token)
                except Exception as e:  # noqa: BLE001
                    self.client.logger(
                        f"vault: renew failed for {task.name}: {e!r}")
                    tr = self.task_runners.get(task.name)
                    # re-derive a fresh token (the failure path after e.g. a
                    # leader failover wiped the in-memory backend), update
                    # the env + secrets file, THEN notify per change_mode
                    try:
                        out = self.client.rpc.vault_derive_token(
                            self.alloc.id, task.name)
                        token = out["token"]
                        self._vault_tokens[task.name] = token
                        if tr is not None:
                            if task.vault.env:
                                tr.env["VAULT_TOKEN"] = token
                            tok_path = os.path.join(tr.task_dir, "secrets",
                                                    "vault_token")
                            fd = os.open(tok_path,
                                         os.O_WRONLY | os.O_CREAT
                                         | os.O_TRUNC, 0o600)
                            with os.fdopen(fd, "w") as f:
                                f.write(token)
                    except Exception as e2:  # noqa: BLE001
                        self.client.logger(
                            f"vault: re-derive failed for {task.name}: "
                            f"{e2!r}")
                        return
                    mode = task.vault.change_mode
                    try:
                        if tr is not None and mode == "restart":
                            tr.restart("vault token rotated")
                        elif tr is not None and mode == "signal":
                            tr.signal(task.vault.change_signal or "SIGHUP",
                                      "vault token rotated")
                    except ValueError:
                        pass   # task not running: nothing to notify
        threading.Thread(target=renew_loop, daemon=True,
                         name=f"vault-renew-{task.name}").start()

    # ------------------------------------------------------------- services

    def _service_instances(self):
        """Build catalog rows for every tg- and task-level service (ref
        command/agent/consul service registration)."""
        from ..integrations.services import ServiceInstance
        alloc = self.alloc
        tg = alloc.job.lookup_task_group(alloc.task_group) \
            if alloc.job else None
        if tg is None:
            return []
        address = (self.client.node.http_addr.rsplit(":", 1)[0]
                   if self.client.node.http_addr else "127.0.0.1")
        out = []

        def port_for(label: str, task_name: str = "") -> int:
            if label.isdigit():
                return int(label)
            tres = alloc.allocated_resources.tasks.get(task_name) \
                if task_name else None
            nets = (tres.networks if tres else []) or []
            for net in nets:
                for p in list(net.reserved_ports) + list(net.dynamic_ports):
                    if p.label == label:
                        return p.value
            # group-network ports live in shared resources
            # (ref scheduler/rank.py shared.ports / structs AllocatedPorts)
            for p in alloc.allocated_resources.shared.ports:
                if p.get("label") == label:
                    return p.get("value", 0)
            return 0

        for svc, task_name in (
                [(s, "") for s in tg.services]
                + [(s, t.name) for t in tg.tasks for s in t.services]):
            checks = [dict(c) for c in svc.checks]
            for c in checks:
                # an exposed check targets its own proxy listener port
                # (connect._expose_admission rewrote its port_label);
                # resolve it here where the allocation's ports are known
                lbl = c.get("port_label") or c.get("PortLabel") or ""
                if lbl:
                    c["port"] = port_for(lbl, task_name)
            out.append((ServiceInstance(
                service_name=svc.name, namespace=alloc.namespace,
                job_id=alloc.job_id, alloc_id=alloc.id,
                node_id=alloc.node_id, task=task_name, address=address,
                port=port_for(svc.port_label, task_name),
                tags=tuple(svc.tags)), checks))
        return out

    def _register_services(self) -> None:
        """Register this alloc's services + spawn check runners. The
        whole body holds _services_lock: a flag-only claim would let
        teardown interleave between the claim and the register RPC,
        leaving the registration leaked server-side forever and check
        runners pushing status for a dead alloc."""
        from ..integrations.services import CheckRunner
        with self._services_lock:
            if self._services_closed or self._services_registered:
                return
            pairs = self._service_instances()
            if not pairs:
                self._services_registered = True    # nothing to register
                return
            try:
                self.client.rpc.service_register(
                    [inst for inst, _ in pairs])
            except Exception as e:      # noqa: BLE001
                self.client.logger(f"service register failed: {e!r}")
                return                  # retried by the sync loop

            def on_status(instance, status):
                instance = instance.copy()
                instance.status = status
                try:
                    self.client.rpc.service_register([instance])
                except Exception as e:  # noqa: BLE001
                    self.client.logger(f"check status push failed: {e!r}")
            self._services_registered = True
            for inst, checks in pairs:
                if checks:
                    cr = CheckRunner(inst, checks, on_status)
                    cr.start()
                    self._check_runners.append(cr)

    def _deregister_services(self) -> None:
        """Terminal: close the service lifecycle (no later register can
        claim), stop check runners, deregister. Serialized against
        _register_services by _services_lock, so whichever side wins the
        race, the final server-side state is deregistered."""
        with self._services_lock:
            self._services_closed = True
            for cr in self._check_runners:
                cr.stop()
            self._check_runners.clear()
            if not self._services_registered:
                return
            self._services_registered = False
            try:
                self.client.rpc.service_deregister(alloc_id=self.alloc.id)
            except Exception as e:      # noqa: BLE001
                self.client.logger(f"service deregister failed: {e!r}")

    def _run_impl(self) -> None:
        alloc = self.alloc
        if alloc.server_terminal_status():
            return
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            self._set_client_status(ALLOC_CLIENT_FAILED,
                                    "task group not found in job")
            return
        os.makedirs(self.alloc_dir, exist_ok=True)

        # previous-alloc wait + ephemeral disk migration (ref
        # client/allocwatcher; the migrate_hook in alloc_runner_hooks.go)
        if alloc.previous_allocation:
            from .alloc_watcher import PrevAllocWatcher
            try:
                PrevAllocWatcher(self.client, alloc,
                                 logger=self.client.logger).wait_and_migrate()
            except Exception as e:      # noqa: BLE001 — best-effort
                self.client.logger(f"allocwatcher: migrate failed: {e!r}")

        # bridge-mode network namespace before any task starts (ref
        # client/allocrunner/network_hook.go Prerun); the netns status is
        # exposed to tasks via NOMAD_ALLOC_IP / NOMAD_ALLOC_NETNS
        try:
            self.network_status = self.client.network_hook.prerun(alloc, tg)
        except Exception as e:          # noqa: BLE001
            self._set_client_status(ALLOC_CLIENT_FAILED,
                                    f"network setup failed: {e}")
            return

        # CSI volumes: claim + stage + publish before any task starts
        # (ref client/allocrunner/csi_hook.go Prerun)
        csi_reqs = [r for r in tg.volumes.values() if r.type == "csi"]
        if csi_reqs:
            try:
                for req in csi_reqs:
                    self.client.csi_manager.mount_volume(alloc, req)
            except Exception as e:      # noqa: BLE001
                self._set_client_status(ALLOC_CLIENT_FAILED,
                                        f"CSI volume mount failed: {e}")
                return

        prestart = [t for t in tg.tasks if t.is_prestart()]
        main = [t for t in tg.tasks
                if t.lifecycle is None or (t.is_prestart() and t.lifecycle.sidecar)]
        poststart = [t for t in tg.tasks if t.is_poststart()]
        poststop = [t for t in tg.tasks if t.is_poststop()]

        # prestart (non-sidecar) must finish before main starts
        # (ref client/allocrunner/task_hook_coordinator.go)
        blockers = []
        for task in prestart:
            tr = self._make_runner(task)
            tr.start()
            if not task.lifecycle.sidecar:
                blockers.append(tr)
        for tr in blockers:
            tr.wait_done()
            if tr.state.failed:
                self._set_client_status(ALLOC_CLIENT_FAILED,
                                        "prestart task failed")
                self._run_poststop(poststop)
                return

        runners = []
        for task in main:
            if task.is_prestart():
                continue  # sidecars already started
            tr = self._make_runner(task)
            tr.start()
            runners.append(tr)
        for task in poststart:
            tr = self._make_runner(task)
            tr.start()
            runners.append(tr)

        for tr in runners:
            tr.wait_done()
        # main work done: stop prestart sidecars
        for task in prestart:
            if task.lifecycle.sidecar:
                tr = self.task_runners.get(task.name)
                if tr:
                    tr.kill("main tasks finished")
                    tr.wait_done(timeout=10)
        self._run_poststop(poststop)
        self._waiters_done.set()

    def _run_poststop(self, tasks) -> None:
        runners = []
        for task in tasks:
            tr = self._make_runner(task)
            tr.start()
            runners.append(tr)
        for tr in runners:
            tr.wait_done(timeout=60)

    def _make_runner(self, task) -> TaskRunner:
        driver = self.client.get_driver(task.driver)
        task_dir = os.path.join(self.alloc_dir, task.name)
        env = build_task_env(self.alloc, task, self.client.node, task_dir,
                             self.alloc_dir,
                             os.path.join(task_dir, "secrets"),
                             network_status=self.network_status)
        # device hook: reserved device instances -> visibility env vars
        # (ref taskrunner/device_hook.go); a reservation failure fails the
        # task rather than launching it without its devices
        setup_error = ""
        # driver config schema validation (the hclspec analog, ref
        # plugins/shared/hclspec): a malformed config fails the task with
        # a decode-style error instead of a mid-start crash
        schema = None
        if driver is not None:
            get_schema = getattr(driver, "config_schema", None)
            schema = get_schema() if get_schema else None
        if schema is not None:
            from .driver import validate_config
            err = validate_config(task.config or {}, schema)
            if err:
                setup_error = f"driver config validation failed: {err}"
                self.client.logger(
                    f"task {task.name!r}: {setup_error}")
        tres = self.alloc.allocated_resources.tasks.get(task.name)
        for ad in (tres.devices if tres else []):
            try:
                res = self.client.device_manager.reserve(ad)
                env.update(res.envs)
            except ValueError as e:
                setup_error = f"device reservation failed: {e}"
                self.client.logger(setup_error)

        # artifact hook: download declared artifacts into the task dir
        # before start; a failure fails setup like the reference's
        # recoverable prestart error (ref taskrunner/artifact_hook.go)
        if task.artifacts and not setup_error:
            from .artifact import ArtifactError, fetch_artifact
            for art in task.artifacts:
                try:
                    fetch_artifact(art, task_dir)
                except ArtifactError as e:
                    setup_error = f"artifact download failed: {e}"
                    self.client.logger(setup_error)
                    break

        rendered: list[tuple[str, str, str]] = []
        # vault hook: derive a task token, expose VAULT_TOKEN + the
        # secrets/vault_token file (ref taskrunner/vault_hook.go)
        if task.vault is not None and not setup_error:
            try:
                out = self.client.rpc.vault_derive_token(self.alloc.id,
                                                         task.name)
                token = out["token"]
                self._vault_tokens[task.name] = token
                self._start_vault_renewal(task, token,
                                          float(out.get("ttl_sec", 3600)))
                if task.vault.env:
                    env["VAULT_TOKEN"] = token
                rendered.append(("secrets/vault_token", token, "0600"))
            except Exception as e:      # noqa: BLE001
                setup_error = f"vault token derivation failed: {e}"
                self.client.logger(setup_error)

        # sids hook: a connect sidecar task gets a SERVICE IDENTITY token
        # (ref taskrunner/sids_hook.go deriving Consul SI tokens) written
        # to secrets/si_token — the credential a real mesh data plane
        # authenticates with. Derivation failure degrades, not fails: the
        # reference retries in the background and so does our next
        # restart; the in-process proxy authorizes via server RPC anyway.
        from ..integrations.connect import PROXY_PREFIX
        if task.name.startswith(PROXY_PREFIX) and not setup_error:
            try:
                out = self.client.rpc.derive_si_token(self.alloc.id,
                                                      task.name)
                rendered.append(("secrets/si_token", out["token"], "0600"))
            except Exception as e:      # noqa: BLE001
                self.client.logger(
                    f"sids: SI token derivation failed for "
                    f"{task.name}: {e!r}")

        # template hook: render embedded templates against env + secrets +
        # the service catalog (ref taskrunner/template_hook.go)
        tmpl_rendered: list = []
        if task.templates and not setup_error:
            from ..integrations.template import TemplateError, render_template
            for tmpl in task.templates:
                try:
                    content = render_template(
                        tmpl.embedded_tmpl, env,
                        secret_reader=self.client.rpc.secret_read,
                        service_lookup=lambda name: self.client.rpc
                        .service_instances(self.alloc.namespace, name))
                    tmpl_rendered.append((tmpl.dest_path or "local/template",
                                          content, tmpl.perms))
                except TemplateError as e:
                    setup_error = f"template render failed: {e}"
                    self.client.logger(setup_error)
                    break
            rendered.extend(tmpl_rendered)

        tr = TaskRunner(self.alloc, task, driver, task_dir, env,
                        self._on_task_state, setup_error=setup_error,
                        rendered_files=rendered)
        with self._lock:
            self.task_runners[task.name] = tr

        # template watch loop: re-render on service/KV/secret change and
        # deliver change_mode (ref template.go handleTemplateRerenders)
        if task.templates and not setup_error:
            from ..integrations.template import TemplateWatcher
            watcher = TemplateWatcher(
                tr, task.templates, env,
                secret_reader=self.client.rpc.secret_read,
                service_lookup=lambda name: self.client.rpc
                .service_instances(self.alloc.namespace, name),
                interval=self.client.template_interval_sec,
                logger=self.client.logger)
            watcher.prime(tmpl_rendered)
            watcher.start()
            with self._lock:
                self._template_watchers[task.name] = watcher
        return tr

    # --------------------------------------------------------------- state

    def _on_task_state(self, task_name: str, state: TaskState) -> None:
        """ref alloc_runner.go:486 handleTaskStateUpdates"""
        with self._lock:
            self.task_states[task_name] = state
            # a task that reached a terminal state takes its template
            # watcher with it — otherwise completed tasks leak a thread
            # polling the catalog and firing doomed change_mode restarts
            if state.state == TASK_STATE_DEAD:
                watcher = self._template_watchers.pop(task_name, None)
                if watcher is not None:
                    watcher.stop()
            # a failed leader/main task takes the others down
            if state.state == TASK_STATE_DEAD and state.failed:
                for name, tr in self.task_runners.items():
                    if name != task_name and not tr.state.failed:
                        tr.kill("sibling task failed")
        if state.state == TASK_STATE_RUNNING \
                and not self._services_registered:
            # first task up: publish the alloc's services (ref the consul
            # group-services + service hooks firing at poststart)
            self._register_services()
        self._dirty.set()
        self.client.alloc_state_updated(self)

    def client_alloc(self) -> Allocation:
        """Roll task states up into the alloc's client view
        (ref alloc_runner.go:653 clientAlloc)."""
        with self._lock:
            states = dict(self.task_states)
        a = self.alloc.copy()
        a.task_states = states
        if not states:
            a.client_status = ALLOC_CLIENT_PENDING
        else:
            any_failed = any(s.failed for s in states.values())
            all_dead = all(s.state == TASK_STATE_DEAD for s in states.values())
            any_running = any(s.state == TASK_STATE_RUNNING
                              for s in states.values())
            if all_dead:
                a.client_status = (ALLOC_CLIENT_FAILED if any_failed
                                   else ALLOC_CLIENT_COMPLETE)
            elif any_failed:
                a.client_status = ALLOC_CLIENT_FAILED
            elif any_running:
                a.client_status = ALLOC_CLIENT_RUNNING
            else:
                a.client_status = ALLOC_CLIENT_PENDING
        a.deployment_status = self._deployment_status(a)
        a.modify_time_unix = time.time()
        return a

    def _deployment_status(self, a: Allocation
                           ) -> Optional[AllocDeploymentStatus]:
        """Deployment health (ref client/allocrunner/health_hook.go +
        allochealth tracker): healthy once all tasks run for
        min_healthy_time; unhealthy on failure."""
        if not self.alloc.deployment_id:
            return self.alloc.deployment_status
        tg = (self.alloc.job.lookup_task_group(self.alloc.task_group)
              if self.alloc.job else None)
        update = tg.update if tg else None
        min_healthy = update.min_healthy_time_sec if update else 10.0
        prev = self.alloc.deployment_status
        canary = bool(prev and prev.canary)
        if a.client_status == ALLOC_CLIENT_FAILED:
            return AllocDeploymentStatus(healthy=False, canary=canary,
                                         timestamp_unix=time.time())
        states = a.task_states
        if states and all(s.state == TASK_STATE_RUNNING and not s.failed
                          for s in states.values()):
            started = max(s.started_at for s in states.values())
            if time.time() - started >= min_healthy:
                return AllocDeploymentStatus(healthy=True, canary=canary,
                                             timestamp_unix=time.time())
        if prev is not None and prev.healthy is not None:
            return prev
        return AllocDeploymentStatus(healthy=None, canary=canary)

    # -------------------------------------------------------------- update

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new alloc version (ref alloc_runner.go:809)."""
        old_desired = self.alloc.desired_status
        self.alloc = alloc
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT) \
           and old_desired not in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            self.stop()

    def stop(self) -> None:
        with self._lock:
            runners = list(self.task_runners.values())
            watchers = list(self._template_watchers.values())
        for w in watchers:
            w.stop()
        for tr in runners:
            tr.kill("alloc stopped by server")
        self._dirty.set()
        self.client.alloc_state_updated(self)

    def destroy(self) -> None:
        self.stop()
        self._destroyed.set()

    def signal(self, task_name: str, sig: str) -> None:
        """Signal one task, or every task when task_name is empty (ref
        client/allocrunner Signal)."""
        from ..structs import TASK_STATE_RUNNING
        with self._lock:
            runners = dict(self.task_runners)
        if task_name:
            tr = runners.get(task_name)
            if tr is None:
                raise ValueError(f"unknown task {task_name!r}")
            tr.signal(sig)
            return
        # all-task signal: act only on running tasks, and check eligibility
        # up front so we never partially apply then error
        eligible = [tr for tr in runners.values()
                    if tr.state.state == TASK_STATE_RUNNING]
        if not eligible:
            raise ValueError("allocation has no running tasks")
        for tr in eligible:
            tr.signal(sig)

    def restart_task(self, task_name: str = "") -> None:
        """Restart one task or the whole alloc (ref allocrunner Restart)."""
        with self._lock:
            runners = dict(self.task_runners)
        if task_name:
            tr = runners.get(task_name)
            if tr is None:
                raise ValueError(f"unknown task {task_name!r}")
            tr.restart()
            return
        eligible = [tr for tr in runners.values()
                    if not tr._done.is_set()]
        if not eligible:
            raise ValueError("allocation has no restartable tasks")
        for tr in eligible:
            tr.restart()

    def stats(self) -> dict:
        """Per-task + rolled-up resource usage (ref
        client/allocrunner AllocStats / structs.AllocResourceUsage)."""
        with self._lock:
            runners = dict(self.task_runners)
        tasks = {name: tr.stats() for name, tr in runners.items()}
        return {
            "ResourceUsage": {
                "MemoryStats": {"RSS": sum(
                    t.get("memory_rss_bytes", 0) for t in tasks.values())},
                "CpuStats": {"TotalTicks": sum(
                    t.get("cpu_total_ticks", 0.0) for t in tasks.values())},
            },
            "Tasks": {
                name: {"ResourceUsage": {
                    "MemoryStats": {"RSS": t.get("memory_rss_bytes", 0)},
                    "CpuStats": {
                        "TotalTicks": t.get("cpu_total_ticks", 0.0),
                        "Percent": t.get("cpu_percent", 0.0)},
                }} for name, t in tasks.items()
            },
            "Timestamp": time.time(),
        }

    def is_done(self) -> bool:
        with self._lock:
            states = dict(self.task_states)
        return bool(states) and all(s.state == TASK_STATE_DEAD
                                    for s in states.values())

    # ------------------------------------------------------------- restore

    def restore(self, handles: dict[str, dict]) -> None:
        """Reattach task runners to live tasks (ref alloc_runner.go:417)."""
        alloc = self.alloc
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            return
        from .driver import TaskHandle
        for task in tg.tasks:
            h = handles.get(task.name)
            if not h:
                continue
            tr = self._make_runner(task)
            handle = TaskHandle(**h)
            if not tr.restore(handle):
                # task died while client was down
                tr.state.state = TASK_STATE_DEAD
                tr.state.failed = True
                tr.state.finished_at = time.time()
                self._on_task_state(task.name, tr.state)

    def persistable_handles(self) -> dict[str, dict]:
        with self._lock:
            out = {}
            for name, tr in self.task_runners.items():
                if tr.handle is not None and \
                   tr.state.state == TASK_STATE_RUNNING:
                    out[name] = {
                        "task_id": tr.handle.task_id,
                        "driver": tr.handle.driver,
                        "pid": tr.handle.pid,
                        "config": tr.handle.config,
                        "started_at": tr.handle.started_at,
                    }
            return out
