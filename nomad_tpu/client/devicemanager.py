"""Client device plugin manager (ref client/devicemanager/manager.go +
plugins/device/device.go DevicePlugin: Fingerprint / Reserve / Stats).

The reference runs device plugins as go-plugin gRPC subprocesses; here the
boundary is the `DevicePlugin` interface. `StaticDevicePlugin` is the
built-in reference implementation (the mock/example device plugin analog):
a fixed set of instances whose reservation exposes an env var with the
reserved ids — the NVIDIA_VISIBLE_DEVICES pattern.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..structs import NodeDevice, NodeDeviceResource


@dataclass
class ContainerReservation:
    """What a task gets for its reserved device ids (ref
    plugins/device/device.go ContainerReservation)."""
    envs: dict[str, str] = field(default_factory=dict)
    mounts: list = field(default_factory=list)
    devices: list = field(default_factory=list)   # host device files


class DevicePlugin:
    """ref plugins/device DevicePlugin"""

    def fingerprint(self) -> list[NodeDeviceResource]:
        raise NotImplementedError

    def reserve(self, device_ids: list[str]) -> ContainerReservation:
        raise NotImplementedError

    def stats(self) -> dict[str, dict]:
        """instance id -> stats map"""
        return {}


class StaticDevicePlugin(DevicePlugin):
    """Fixed device inventory (the example/mock device plugin pattern)."""

    def __init__(self, vendor: str, type_: str, name: str,
                 instance_ids: list[str],
                 env_var: str = "", attributes: dict | None = None):
        self.vendor = vendor
        self.type = type_
        self.name = name
        self.instance_ids = list(instance_ids)
        self.unhealthy: set[str] = set()
        self.env_var = env_var or \
            f"{vendor}_{type_}_VISIBLE_DEVICES".upper().replace("-", "_")
        self.attributes = dict(attributes or {})

    def fingerprint(self) -> list[NodeDeviceResource]:
        return [NodeDeviceResource(
            vendor=self.vendor, type=self.type, name=self.name,
            instances=[NodeDevice(id=i, healthy=i not in self.unhealthy)
                       for i in self.instance_ids],
            attributes=dict(self.attributes))]

    def reserve(self, device_ids: list[str]) -> ContainerReservation:
        unknown = [i for i in device_ids if i not in self.instance_ids]
        if unknown:
            raise ValueError(f"unknown device ids {unknown}")
        return ContainerReservation(
            envs={self.env_var: ",".join(device_ids)})

    def stats(self) -> dict[str, dict]:
        return {i: {"healthy": i not in self.unhealthy}
                for i in self.instance_ids}


class DeviceManager:
    """ref client/devicemanager: owns plugins, folds their fingerprints
    into the node, and serves task reservations."""

    def __init__(self, client):
        self.client = client
        self.plugins: dict[tuple[str, str, str], DevicePlugin] = {}

    def register_plugin(self, plugin: DevicePlugin) -> None:
        for group in plugin.fingerprint():
            self.plugins[group.id_tuple()] = plugin

    def fingerprint(self) -> list[NodeDeviceResource]:
        out = []
        seen = set()
        for plugin in self.plugins.values():
            if id(plugin) in seen:
                continue
            seen.add(id(plugin))
            out.extend(plugin.fingerprint())
        return out

    def reserve(self, allocated_device) -> ContainerReservation:
        """AllocatedDeviceResource -> reservation (ref manager.go Reserve)."""
        key = (allocated_device.vendor, allocated_device.type,
               allocated_device.name)
        plugin = self.plugins.get(key)
        if plugin is None:
            raise ValueError(f"no device plugin for {key}")
        return plugin.reserve(list(allocated_device.device_ids))

    def all_stats(self) -> dict:
        out = {}
        for key, plugin in self.plugins.items():
            out["/".join(k for k in key if k)] = plugin.stats()
        return out
