"""Task log rotation (ref client/logmon/logmon.go + lib/fifo: the reference
runs a logmon subprocess per task collecting FIFO output into size-capped
rotated files).

Here drivers append directly to `<task>.{stdout,stderr}.log` (O_APPEND), so
rotation is copy-truncate: when the live file exceeds its cap it is copied
to `<name>.N` (N growing, oldest pruned past max_files) and truncated in
place — writers never need to reopen, matching the logmon contract that
tasks are unaware of rotation.
"""
from __future__ import annotations

import os
import threading

MB = 1024 * 1024


class LogRotator:
    """Watches a task's two log streams and rotates them by size."""

    def __init__(self, task_dir: str, task_name: str, log_config,
                 check_interval: float = 2.0):
        self.task_dir = task_dir
        self.task_name = task_name
        self.max_files = max(1, getattr(log_config, "max_files", 10))
        self.max_bytes = max(64 * 1024,
                             getattr(log_config, "max_file_size_mb", 10) * MB)
        self.check_interval = check_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"logmon-{self.task_name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval):
            self.rotate_if_needed()

    # ----------------------------------------------------------- rotation

    def _stream_path(self, stream: str) -> str:
        return os.path.join(self.task_dir,
                            f"{self.task_name}.{stream}.log")

    def rotate_if_needed(self) -> int:
        """Rotate any stream over its cap; returns number rotated."""
        n = 0
        for stream in ("stdout", "stderr"):
            path = self._stream_path(stream)
            try:
                if os.path.getsize(path) >= self.max_bytes:
                    self._rotate(path)
                    n += 1
            except OSError:
                continue
        return n

    def _rotate(self, path: str) -> None:
        # shift the numbered chain up; drop the oldest beyond max_files-1
        # (the live file counts against max_files, ref logmon rotator.go)
        keep = self.max_files - 1
        for i in range(keep, 0, -1):
            src = f"{path}.{i}"
            if not os.path.exists(src):
                continue
            if i >= keep:
                os.unlink(src)
            else:
                os.replace(src, f"{path}.{i + 1}")
        # copy a size snapshot, then keep any bytes appended during the
        # copy: read the tail past the snapshot, rewrite it at offset 0,
        # truncate to the tail. O_APPEND writers land at the new EOF, so
        # only appends inside the read->truncate instant can be lost (the
        # reference avoids even that by owning the write path via FIFO).
        size = os.path.getsize(path)
        if keep >= 1:
            # log-rotation copy of a task output stream: loss-tolerant
            # data, fsyncing every rotation would tax the client for
            # bytes nobody re-reads after a crash
            # nomadlint: disable=DUR001 — loss-tolerant log stream
            with open(path, "rb") as src, open(f"{path}.1", "wb") as dst:
                remaining = size
                while remaining > 0:
                    chunk = src.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    dst.write(chunk)
                    remaining -= len(chunk)
        with open(path, "r+b") as f:
            f.seek(size)
            tail = f.read()
            f.seek(0)
            if tail:
                f.write(tail)
            f.truncate(len(tail))

    def rotated_files(self, stream: str = "stdout") -> list[str]:
        path = self._stream_path(stream)
        out = [f"{path}.{i}" for i in range(1, self.max_files)
               if os.path.exists(f"{path}.{i}")]
        return out
