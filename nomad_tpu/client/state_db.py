"""Client-local state persistence (ref client/state/state_database.go:123
BoltStateDB): allocs + task handles survive client restarts so a restarted
client reattaches to live tasks instead of killing them."""
from __future__ import annotations

import fcntl
import os
import pickle
import tempfile
import threading
import uuid
from contextlib import contextmanager

from ..structs import Allocation


class StateDB:
    """Durable map of alloc -> (alloc snapshot, task handles). File-backed
    pickle with atomic replace; the interface mirrors the reference's
    (PutAllocation / GetAllAllocations / PutTaskRunnerHandle /
    DeleteAllocationBucket).

    Concurrency model (VERDICT r3 #4): bolt gives the reference a single
    writer via its OS file lock; here the NEWEST StateDB instance on a path
    owns it. __init__ registers an ownership token under an flock'd
    critical section; every flush re-checks ownership inside the same lock
    and silently drops the write when superseded — so a restarted client's
    db can never be clobbered by the dying instance's in-flight background
    flush (stale-snapshot overwrite), and two writers can never consume
    each other's tmp files. Orphaned tmps from SIGKILL'd flushes are swept
    at startup inside the lock (no live writer can be mid-flush there).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._instance = uuid.uuid4().hex
        self._allocs: dict[str, Allocation] = {}
        self._handles: dict[str, dict[str, dict]] = {}
        self._node_id: str = ""
        self._superseded = False
        self._seq = 0             # snapshot sequence, under self._lock
        self._written_seq = 0     # last flushed sequence, under _flush_lock
        with self._flocked():
            self._load()
            self._sweep_tmps()
            # supersession is ordered by a monotonic generation read under
            # the same flock, so "newest instance" is well-defined even
            # if the owner file is later deleted out from under us
            gen, _ = self._read_owner()
            self._gen = gen + 1
            self._claim_ownership()

    # ------------------------------------------------------ cross-instance

    @contextmanager
    def _flocked(self):
        """Exclusive advisory lock serializing load/sweep/flush across
        instances AND processes (flock treats separate fds independently,
        so two instances in one process exclude each other too)."""
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd = os.open(self.path + ".lock", os.O_CREAT | os.O_RDWR, 0o600)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)            # releases the lock

    def _owner_path(self) -> str:
        return self.path + ".owner"

    def _claim_ownership(self) -> None:
        with open(self._owner_path(), "w") as f:
            f.write(f"{self._gen} {self._instance}")

    def _read_owner(self) -> tuple[int, str]:
        """-> (generation, token); (0, "") when missing/unparseable."""
        try:
            with open(self._owner_path()) as f:
                gen_s, _, token = f.read().partition(" ")
            return int(gen_s), token
        except (OSError, ValueError):
            return 0, ""

    def _is_owner(self) -> bool:
        """Must be called under the flock. A MISSING owner file (operator
        tmp-clean, data-dir surgery) is reclaimed rather than treated as
        'not us' — otherwise the sole live client would silently drop
        every flush forever. Reclaim is GENERATION-ordered and every
        reclaim BUMPS the generation past what was read: two instances
        that both re-derive the same generation after a deletion can't
        ping-pong — the first reclaimer's bump makes the other observe a
        strictly greater generation and stand down, so the newest
        writer's state converges on top. Supersession is STICKY (ADVICE
        r4): once this instance has ever observed a higher generation it
        refuses reclaim forever — otherwise deleting the owner file lets
        a superseded instance that flushes first overwrite the newer
        instance's db with its stale snapshot."""
        if self._superseded:
            return False
        gen, token = self._read_owner()
        if token == self._instance:
            return True
        if gen > self._gen:
            self._superseded = True     # a newer instance owns the path
            return False
        self._gen = max(self._gen, gen) + 1
        self._claim_ownership()         # missing, or a stale reclaimer
        return True

    def _sweep_tmps(self) -> None:
        """Remove tmps orphaned by a SIGKILL mid-flush. Safe only under
        the flock: no live writer can be between mkstemp and rename."""
        d = os.path.dirname(self.path) or "."
        base = os.path.basename(self.path) + "."
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if name.startswith(base) and name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass

    # ------------------------------------------------------------ persist

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path, "rb") as f:
                blob = pickle.load(f)
            self._allocs = blob.get("allocs", {})
            self._handles = blob.get("handles", {})
            self._node_id = blob.get("node_id", "")
        except Exception:
            # corrupt state: start fresh (the reference logs + recovers too)
            self._allocs, self._handles = {}, {}

    def _snapshot(self) -> tuple:
        """Consistent copy of the persisted maps + a sequence number.
        Must be called under self._lock (the shallow dict copies are the
        write-isolation boundary — Allocation values are replaced, never
        mutated, by the client's update paths)."""
        self._seq += 1
        return (self._seq, dict(self._allocs), dict(self._handles),
                self._node_id)

    def _flush_snapshot(self, snap: tuple) -> None:
        """Persist a snapshot OUTSIDE self._lock (ADVICE r4: awaiting the
        inter-process flock while holding the thread lock lets a
        contending sidecar process stall every StateDB API call). The
        flush mutex serializes same-process flushers; the sequence check
        drops a snapshot that lost the race to a newer one, so writes
        can't go back in time. Tmp-per-writer + fsync + atomic rename,
        all inside the flock. The ownership re-check makes a superseded
        instance's flush a no-op instead of a stale overwrite."""
        seq, allocs, handles, node_id = snap
        d = os.path.dirname(self.path) or "."
        with self._flush_lock, self._flocked():
            if seq <= self._written_seq:
                return              # a newer snapshot already landed
            if not self._is_owner():
                return              # superseded by a newer instance
            self._written_seq = seq
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(self.path) + ".", suffix=".tmp",
                dir=d)
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump({"allocs": allocs,
                                 "handles": handles,
                                 "node_id": node_id}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
                # fsync the directory so the rename itself survives power
                # loss (file fsync alone doesn't journal the dir entry)
                dfd = os.open(d, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

    # ---------------------------------------------------------------- API

    def put_node_id(self, node_id: str) -> None:
        with self._lock:
            self._node_id = node_id
            snap = self._snapshot()
        self._flush_snapshot(snap)

    def get_node_id(self) -> str:
        with self._lock:
            return self._node_id

    def put_allocation(self, alloc: Allocation) -> None:
        with self._lock:
            self._allocs[alloc.id] = alloc
            snap = self._snapshot()
        self._flush_snapshot(snap)

    def get_all_allocations(self) -> list[Allocation]:
        with self._lock:
            return list(self._allocs.values())

    def put_task_handles(self, alloc_id: str,
                         handles: dict[str, dict]) -> None:
        with self._lock:
            self._handles[alloc_id] = handles
            snap = self._snapshot()
        self._flush_snapshot(snap)

    def get_task_handles(self, alloc_id: str) -> dict[str, dict]:
        with self._lock:
            return dict(self._handles.get(alloc_id, {}))

    def delete_allocation(self, alloc_id: str) -> None:
        with self._lock:
            self._allocs.pop(alloc_id, None)
            self._handles.pop(alloc_id, None)
            snap = self._snapshot()
        self._flush_snapshot(snap)
