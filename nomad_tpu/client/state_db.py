"""Client-local state persistence (ref client/state/state_database.go:123
BoltStateDB): allocs + task handles survive client restarts so a restarted
client reattaches to live tasks instead of killing them."""
from __future__ import annotations

import json
import os
import pickle
import threading
from typing import Optional

from ..structs import Allocation


class StateDB:
    """Durable map of alloc -> (alloc snapshot, task handles). File-backed
    pickle with atomic replace; the interface mirrors the reference's
    (PutAllocation / GetAllAllocations / PutTaskRunnerHandle /
    DeleteAllocationBucket)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._allocs: dict[str, Allocation] = {}
        self._handles: dict[str, dict[str, dict]] = {}
        self._node_id: str = ""
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path, "rb") as f:
                blob = pickle.load(f)
            self._allocs = blob.get("allocs", {})
            self._handles = blob.get("handles", {})
            self._node_id = blob.get("node_id", "")
        except Exception:
            # corrupt state: start fresh (the reference logs + recovers too)
            self._allocs, self._handles = {}, {}

    def _flush_locked(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"allocs": self._allocs, "handles": self._handles,
                         "node_id": self._node_id}, f)
        os.replace(tmp, self.path)

    def put_node_id(self, node_id: str) -> None:
        with self._lock:
            self._node_id = node_id
            self._flush_locked()

    def get_node_id(self) -> str:
        with self._lock:
            return self._node_id

    def put_allocation(self, alloc: Allocation) -> None:
        with self._lock:
            self._allocs[alloc.id] = alloc
            self._flush_locked()

    def get_all_allocations(self) -> list[Allocation]:
        with self._lock:
            return list(self._allocs.values())

    def put_task_handles(self, alloc_id: str,
                         handles: dict[str, dict]) -> None:
        with self._lock:
            self._handles[alloc_id] = handles
            self._flush_locked()

    def get_task_handles(self, alloc_id: str) -> dict[str, dict]:
        with self._lock:
            return dict(self._handles.get(alloc_id, {}))

    def delete_allocation(self, alloc_id: str) -> None:
        with self._lock:
            self._allocs.pop(alloc_id, None)
            self._handles.pop(alloc_id, None)
            self._flush_locked()
