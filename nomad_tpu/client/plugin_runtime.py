"""Plugin SDK: host a Driver or CSI plugin implementation as an external
plugin process (ref plugins/base/plugin.go Serve + plugins/drivers and
plugins/csi gRPC servers).

A third-party driver is a Python script:

    from nomad_tpu.client.driver import Driver
    from nomad_tpu.client.plugin_runtime import serve_driver

    class MyDriver(Driver):
        name = "my-driver"
        ...

    if __name__ == "__main__":
        serve_driver(MyDriver())

A CSI plugin is the same shape around serve_csi(MyCSIPlugin()). The host
(client agent) launches the executable, reads the handshake line, and
proxies the in-process interface over the unix socket (see
plugin_host.py for the frame protocol)."""
from __future__ import annotations

import os
import socket
import sys
import tempfile
import threading

from .plugin_host import (
    HANDSHAKE_PREFIX, MAGIC_ENV, MAGIC_VALUE, SUPPORTED_PROTOCOLS,
    _recv_frame, _send_frame,
)


def _serve(info: dict, dispatch) -> None:
    """Common plugin server: magic-cookie gate, socket bind, handshake
    announce, then framed RPC until the host sends Shutdown.
    `dispatch(method, params)` returns the result or raises."""
    if os.environ.get(MAGIC_ENV) != MAGIC_VALUE:
        print("This binary is a nomad_tpu plugin and must be launched "
              "by the client agent, not run directly.", file=sys.stderr)
        sys.exit(1)

    sock_path = os.path.join(
        tempfile.mkdtemp(prefix="nomad-plugin-"), "plugin.sock")
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(4)
    versions = ",".join(str(v) for v in SUPPORTED_PROTOCOLS)
    print(f"{HANDSHAKE_PREFIX}{versions}|{sock_path}", flush=True)

    stop = threading.Event()

    def handle(conn: socket.socket) -> None:
        while not stop.is_set():
            try:
                req = _recv_frame(conn)
            except (OSError, ValueError):
                return
            if req is None:
                return
            rid = req.get("id")
            method = req.get("method", "")
            params = req.get("params", {}) or {}
            try:
                if method == "PluginInfo":
                    result = dict(info,
                                  protocols=list(SUPPORTED_PROTOCOLS))
                elif method == "Shutdown":
                    result = {}
                    stop.set()
                else:
                    result = dispatch(method, params)
                _send_frame(conn, {"id": rid, "result": result})
            except Exception as e:      # noqa: BLE001 - report, keep serving
                _send_frame(conn, {"id": rid, "error": str(e),
                                   "kind": type(e).__name__})
        try:
            conn.close()
        except OSError:
            pass

    while not stop.is_set():
        try:
            srv.settimeout(0.5)
            conn, _ = srv.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        threading.Thread(target=handle, args=(conn,), daemon=True).start()
    srv.close()


def serve_driver(driver, version: str = "0.1.0") -> None:
    """Blocking: announce the handshake and serve driver RPCs until the
    host disconnects or sends Shutdown."""
    # exec sessions are process-global: the host may open a session on
    # one connection and poll it from another (ref the reference's
    # per-stream gRPC exec living beside unary task RPCs)
    sessions: dict[str, object] = {}
    sessions_lock = threading.Lock()

    def dispatch(method: str, params: dict):
        from ..api_codec import from_api
        from ..structs.job import Task
        if method == "Fingerprint":
            fp = driver.fingerprint()
            return {"detected": fp.detected, "healthy": fp.healthy,
                    "attributes": dict(fp.attributes)}
        if method == "StartTask":
            task = from_api(Task, params["task"])
            h = driver.start_task(params["task_id"], task,
                                  params["task_dir"],
                                  params.get("env", {}))
            return {"pid": h.pid, "started_at": h.started_at}
        if method == "WaitTask":
            r = driver.wait_task(params["task_id"], params.get("timeout"))
            return None if r is None else {
                "exit_code": r.exit_code, "signal": r.signal,
                "err": r.err}
        if method == "StopTask":
            driver.stop_task(params["task_id"],
                             params.get("kill_timeout", 5.0),
                             params.get("sig", ""))
            return {}
        if method == "DestroyTask":
            driver.destroy_task(params["task_id"])
            return {}
        if method == "SignalTask":
            driver.signal_task(params["task_id"], params["sig"])
            return {}
        if method == "TaskStats":
            return driver.task_stats(params["task_id"])
        if method == "InspectTask":
            h = driver.inspect_task(params["task_id"])
            return None if h is None else {"pid": h.pid}
        if method == "RecoverTask":
            from .driver import TaskHandle
            return driver.recover_task(TaskHandle(
                task_id=params["task_id"], driver=driver.name,
                pid=int(params.get("pid", 0))))
        if method == "ExecOpen":
            # streaming exec across the plugin boundary (ref
            # plugins/drivers/driver.go:577 ExecTaskStreamingRaw)
            import uuid
            sess = driver.exec_task(
                params["task_id"], params.get("command") or [],
                tty=bool(params.get("tty")),
                cwd=params.get("cwd", ""),
                env=params.get("env") or {})
            sid = uuid.uuid4().hex
            with sessions_lock:
                sessions[sid] = sess
            return {"session": sid}
        if method in ("ExecIO", "ExecResize", "ExecClose"):
            import base64
            with sessions_lock:
                sess = sessions.get(params["session"])
            if sess is None:
                raise ValueError("unknown exec session")
            if method == "ExecResize":
                sess.resize(int(params.get("rows", 24)),
                            int(params.get("cols", 80)))
                return {}
            if method == "ExecClose":
                with sessions_lock:
                    sessions.pop(params["session"], None)
                sess.terminate()
                return {}
            if params.get("stdin"):
                sess.write_stdin(base64.b64decode(params["stdin"]))
            if params.get("close_stdin"):
                sess.close_stdin()
            out = sess.read_output(float(params.get("wait", 0.0)))
            return {"stdout": base64.b64encode(out["stdout"]).decode(),
                    "stderr": base64.b64encode(out["stderr"]).decode(),
                    "exited": out["exited"],
                    "exit_code": out["exit_code"]}
        raise ValueError(f"unknown plugin method {method!r}")

    _serve({"type": "driver", "name": driver.name, "version": version},
           dispatch)


def serve_csi(plugin, version: str = "0.1.0") -> None:
    """Blocking: serve a CSIPluginClient implementation as an external
    CSI plugin process (ref plugins/csi/client.go — the reference's CSI
    drivers are separate gRPC processes; this is that boundary)."""

    def dispatch(method: str, params: dict):
        if method == "Fingerprint":
            return plugin.fingerprint()
        if method == "NodeStageVolume":
            plugin.node_stage_volume(params["volume_id"],
                                     params.get("context") or {})
            return {}
        if method == "NodePublishVolume":
            plugin.node_publish_volume(
                params["volume_id"], params["target_path"],
                bool(params.get("readonly")),
                params.get("context") or {})
            return {}
        if method == "NodeUnpublishVolume":
            plugin.node_unpublish_volume(params["volume_id"],
                                         params["target_path"])
            return {}
        if method == "ControllerUnpublishVolume":
            plugin.controller_unpublish_volume(params["volume_id"],
                                               params["node_id"])
            return {}
        raise ValueError(f"unknown plugin method {method!r}")

    _serve({"type": "csi", "name": plugin.name, "version": version,
            "requires_controller": bool(
                getattr(plugin, "requires_controller", False))},
           dispatch)
