"""Driver plugin SDK: host a Driver implementation as an external plugin
process (ref plugins/base/plugin.go Serve + plugins/drivers gRPC server).

A third-party driver is a Python script:

    from nomad_tpu.client.driver import Driver
    from nomad_tpu.client.plugin_runtime import serve_driver

    class MyDriver(Driver):
        name = "my-driver"
        ...

    if __name__ == "__main__":
        serve_driver(MyDriver())

The host (client agent) launches it, reads the handshake line, and
proxies the Driver interface over the unix socket (see plugin_host.py
for the frame protocol)."""
from __future__ import annotations

import json
import os
import socket
import struct
import sys
import tempfile
import threading

from .plugin_host import (
    HANDSHAKE_PREFIX, MAGIC_ENV, MAGIC_VALUE, SUPPORTED_PROTOCOLS,
    _recv_frame, _send_frame,
)


def serve_driver(driver, version: str = "0.1.0") -> None:
    """Blocking: announce the handshake and serve driver RPCs until the
    host disconnects or sends Shutdown."""
    if os.environ.get(MAGIC_ENV) != MAGIC_VALUE:
        print("This binary is a nomad_tpu driver plugin and must be "
              "launched by the client agent, not run directly.",
              file=sys.stderr)
        sys.exit(1)

    sock_path = os.path.join(
        tempfile.mkdtemp(prefix="nomad-plugin-"), "plugin.sock")
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(4)
    versions = ",".join(str(v) for v in SUPPORTED_PROTOCOLS)
    print(f"{HANDSHAKE_PREFIX}{versions}|{sock_path}", flush=True)

    stop = threading.Event()
    # exec sessions are process-global: the host may open a session on
    # one connection and poll it from another (ref the reference's
    # per-stream gRPC exec living beside unary task RPCs)
    sessions: dict[str, object] = {}
    sessions_lock = threading.Lock()

    def handle(conn: socket.socket) -> None:
        from ..api_codec import from_api
        from ..structs.job import Task
        while not stop.is_set():
            try:
                req = _recv_frame(conn)
            except (OSError, ValueError):
                return
            if req is None:
                return
            rid = req.get("id")
            method = req.get("method", "")
            params = req.get("params", {}) or {}
            try:
                if method == "PluginInfo":
                    result = {"type": "driver", "name": driver.name,
                              "version": version,
                              "protocols": list(SUPPORTED_PROTOCOLS)}
                elif method == "Shutdown":
                    result = {}
                    stop.set()
                elif method == "Fingerprint":
                    fp = driver.fingerprint()
                    result = {"detected": fp.detected,
                              "healthy": fp.healthy,
                              "attributes": dict(fp.attributes)}
                elif method == "StartTask":
                    task = from_api(Task, params["task"])
                    h = driver.start_task(params["task_id"], task,
                                          params["task_dir"],
                                          params.get("env", {}))
                    result = {"pid": h.pid, "started_at": h.started_at}
                elif method == "WaitTask":
                    r = driver.wait_task(params["task_id"],
                                         params.get("timeout"))
                    result = None if r is None else {
                        "exit_code": r.exit_code, "signal": r.signal,
                        "err": r.err}
                elif method == "StopTask":
                    driver.stop_task(params["task_id"],
                                     params.get("kill_timeout", 5.0),
                                     params.get("sig", ""))
                    result = {}
                elif method == "DestroyTask":
                    driver.destroy_task(params["task_id"])
                    result = {}
                elif method == "SignalTask":
                    driver.signal_task(params["task_id"], params["sig"])
                    result = {}
                elif method == "TaskStats":
                    result = driver.task_stats(params["task_id"])
                elif method == "InspectTask":
                    h = driver.inspect_task(params["task_id"])
                    result = None if h is None else {"pid": h.pid}
                elif method == "RecoverTask":
                    from .driver import TaskHandle
                    result = driver.recover_task(TaskHandle(
                        task_id=params["task_id"], driver=driver.name,
                        pid=int(params.get("pid", 0))))
                elif method == "ExecOpen":
                    # streaming exec across the plugin boundary (ref
                    # plugins/drivers/driver.go:577 ExecTaskStreamingRaw)
                    import uuid
                    sess = driver.exec_task(
                        params["task_id"], params.get("command") or [],
                        tty=bool(params.get("tty")),
                        cwd=params.get("cwd", ""),
                        env=params.get("env") or {})
                    sid = uuid.uuid4().hex
                    with sessions_lock:
                        sessions[sid] = sess
                    result = {"session": sid}
                elif method in ("ExecIO", "ExecResize", "ExecClose"):
                    import base64
                    with sessions_lock:
                        sess = sessions.get(params["session"])
                    if sess is None:
                        raise ValueError("unknown exec session")
                    if method == "ExecResize":
                        sess.resize(int(params.get("rows", 24)),
                                    int(params.get("cols", 80)))
                        result = {}
                    elif method == "ExecClose":
                        with sessions_lock:
                            sessions.pop(params["session"], None)
                        sess.terminate()
                        result = {}
                    else:
                        if params.get("stdin"):
                            sess.write_stdin(
                                base64.b64decode(params["stdin"]))
                        if params.get("close_stdin"):
                            sess.close_stdin()
                        out = sess.read_output(
                            float(params.get("wait", 0.0)))
                        result = {
                            "stdout": base64.b64encode(
                                out["stdout"]).decode(),
                            "stderr": base64.b64encode(
                                out["stderr"]).decode(),
                            "exited": out["exited"],
                            "exit_code": out["exit_code"]}
                else:
                    raise ValueError(f"unknown plugin method {method!r}")
                _send_frame(conn, {"id": rid, "result": result})
            except Exception as e:      # noqa: BLE001 - report, keep serving
                _send_frame(conn, {"id": rid, "error": str(e),
                                   "kind": type(e).__name__})
        try:
            conn.close()
        except OSError:
            pass

    while not stop.is_set():
        try:
            srv.settimeout(0.5)
            conn, _ = srv.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        threading.Thread(target=handle, args=(conn,), daemon=True).start()
    srv.close()
