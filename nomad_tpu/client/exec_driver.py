"""exec driver backed by the native C++ executor supervisor
(ref drivers/exec + drivers/shared/executor: the re-exec'd subprocess
boundary, here a compiled sidecar binary).

Each task gets one `nomad-executor` process that owns the task's session,
applies resource limits, supervises the workload, and persists the exit
status to a result file — so task state survives client restarts (the
reattach contract, ref task_runner.go:1129).
"""
from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import threading
import time
from typing import Optional

from ..structs import DriverInfo
from .driver import Driver, ExitResult, TaskHandle

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BIN = os.path.join(_REPO_ROOT, "native", "nomad-executor")

_build_lock = threading.Lock()


def ensure_executor_binary(path: str = DEFAULT_BIN) -> Optional[str]:
    """Build the executor on first use (g++ baked into the image)."""
    if os.path.exists(path):
        return path
    with _build_lock:
        if os.path.exists(path):
            return path
        src_dir = os.path.dirname(path)
        if not os.path.exists(os.path.join(src_dir, "executor.cc")):
            return None
        try:
            subprocess.run(["make", "-C", src_dir], check=True,
                           capture_output=True, timeout=120)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                FileNotFoundError):
            return None
        return path if os.path.exists(path) else None


def _cgroup_parent() -> str:
    """A writable cgroup v2 parent for task leaves, or "" (the executor
    then falls back to rlimit/nice). Prefers a dedicated nomad-tpu group
    under the root; inside a delegated container, the process's own
    cgroup is the only writable subtree (ref cgutil.CgroupScope)."""
    root = "/sys/fs/cgroup"
    if not os.path.exists(os.path.join(root, "cgroup.controllers")):
        return ""                        # not unified cgroup v2
    dedicated = os.path.join(root, "nomad-tpu")
    try:
        os.makedirs(dedicated, exist_ok=True)
        if os.access(dedicated, os.W_OK):    # a pre-existing root-owned
            return dedicated                 # dir must not shadow the
    except OSError:                          # delegated-cgroup fallback
        pass
    try:
        with open("/proc/self/cgroup") as f:
            for line in f:
                if line.startswith("0::"):
                    own = root + line.split("::", 1)[1].strip()
                    if os.access(own, os.W_OK):
                        return own
    except OSError:
        pass
    return ""


class ExecDriver(Driver):
    """config keys: command, args; resources drive the limits."""

    name = "exec"

    def __init__(self, executor_bin: str = DEFAULT_BIN):
        self.executor_bin = executor_bin
        self._lock = threading.Lock()
        # task_id -> {proc or pid, result_path}
        self._tasks: dict[str, dict] = {}

    def fingerprint(self) -> DriverInfo:
        ok = ensure_executor_binary(self.executor_bin) is not None
        return DriverInfo(detected=ok, healthy=ok,
                          health_description="" if ok
                          else "nomad-executor binary unavailable",
                          attributes={"driver.exec.executor": "native"})

    def start_task(self, task_id: str, task, task_dir: str,
                   env: dict[str, str]) -> TaskHandle:
        binary = ensure_executor_binary(self.executor_bin)
        if binary is None:
            raise RuntimeError("nomad-executor binary unavailable")
        cfg = task.config
        command = cfg.get("command", "")
        if not command:
            raise ValueError("exec requires config.command")
        args = cfg.get("args", [])
        if isinstance(args, str):
            args = shlex.split(args)

        safe_id = task_id.replace("/", "_")
        spec_path = os.path.join(task_dir, f".{safe_id}.spec")
        result_path = os.path.join(task_dir, f".{safe_id}.result.json")
        pid_path = os.path.join(task_dir, f".{safe_id}.pid")
        for stale in (result_path, pid_path):
            if os.path.exists(stale):
                os.unlink(stale)

        full_env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin")}
        full_env.update(env)

        # execve does no PATH search: resolve bare commands against the
        # task's PATH (matching the raw_exec/Popen behavior)
        if "/" not in command:
            import shutil
            resolved = shutil.which(command, path=full_env.get("PATH"))
            if resolved is None:
                raise ValueError(f"command {command!r} not found on PATH")
            command = resolved

        # the spec file is line-oriented: embedded newlines would inject
        # directives (e.g. a second command=), so reject them outright
        for label, value in ([("command", command)] +
                             [("arg", a) for a in args] +
                             [(f"env {k}", f"{k}={v}")
                              for k, v in full_env.items()]):
            if "\n" in str(value) or "\r" in str(value):
                raise ValueError(f"{label} contains a newline")

        lines = [f"command={command}"]
        lines += [f"arg={a}" for a in args]
        lines += [f"env={k}={v}" for k, v in full_env.items()]
        lines += [
            f"cwd={task_dir}",
            f"stdout={os.path.join(task_dir, task.name + '.stdout.log')}",
            f"stderr={os.path.join(task_dir, task.name + '.stderr.log')}",
            f"result={result_path}",
            f"pidfile={pid_path}",
            f"memory_mb={task.resources.memory_mb or 0}",
            f"cpu_nice={int(cfg.get('nice', 0))}",
            f"cpu_shares={task.resources.cpu or 0}",
        ]
        cg = _cgroup_parent()
        if cg:
            lines.append(f"cgroup_parent={cg}")
        with open(spec_path, "w") as f:
            f.write("\n".join(lines) + "\n")

        proc = subprocess.Popen([binary, spec_path],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL,
                                start_new_session=True)
        with self._lock:
            self._tasks[task_id] = {"pid": proc.pid, "proc": proc,
                                    "result": result_path,
                                    "pidfile": pid_path}
        return TaskHandle(task_id=task_id, driver=self.name, pid=proc.pid,
                          config={"result": result_path,
                                  "pidfile": pid_path},
                          started_at=time.time())

    def wait_task(self, task_id: str, timeout: Optional[float] = None
                  ) -> Optional[ExitResult]:
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return ExitResult(err="unknown task")
        deadline = time.time() + timeout if timeout is not None else None
        while True:
            result = self._read_result(rec["result"])
            if result is not None:
                return result
            if not self._executor_alive(rec):
                # the executor may have written the result between our two
                # checks — re-read before declaring it dead
                time.sleep(0.05)
                result = self._read_result(rec["result"])
                if result is not None:
                    return result
                self._kill_task_group(rec)   # don't leak the task tree
                return ExitResult(exit_code=-1, err="executor died")
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(0.05)

    def _read_result(self, path: str) -> Optional[ExitResult]:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        return ExitResult(exit_code=int(data.get("exit_code", -1)),
                          signal=int(data.get("signal", 0)),
                          err=data.get("err", ""))

    def _executor_alive(self, rec: dict) -> bool:
        proc = rec.get("proc")
        if proc is not None:
            return proc.poll() is None
        try:
            os.kill(rec["pid"], 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True

    def stop_task(self, task_id: str, kill_timeout: float = 5.0,
                  sig: str = "") -> None:
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return
        signum = getattr(signal, sig, signal.SIGTERM) if sig else signal.SIGTERM
        try:
            os.kill(rec["pid"], signum)   # executor forwards to the task group
        except ProcessLookupError:
            return
        deadline = time.time() + kill_timeout
        while time.time() < deadline:
            if self._read_result(rec["result"]) is not None or \
               not self._executor_alive(rec):
                return
            time.sleep(0.05)
        # escalation: the task ignored its signal — SIGKILL the TASK's
        # process group (from the pidfile), then give the executor a
        # moment to reap the child and persist the result. SIGKILLing
        # the executor immediately (the old order) raced its waitpid:
        # in a container whose PID 1 never reaps orphans, the killed
        # child stayed a zombie forever and `kill(child, 0)` kept
        # succeeding — the task looked alive after a confirmed kill.
        self._kill_task_group(rec)
        reap_deadline = time.time() + 2.0
        while time.time() < reap_deadline:
            if self._read_result(rec["result"]) is not None or \
               not self._executor_alive(rec):
                return
            time.sleep(0.02)
        try:
            os.kill(rec["pid"], signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def signal_task(self, task_id: str, sig: str) -> None:
        """Signal the task's process group directly (the executor's child,
        from the pidfile) — ref executor Signal RPC."""
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            raise ValueError("unknown task")
        signum = getattr(signal, sig, None)
        if signum is None:
            raise ValueError(f"invalid signal {sig!r}")
        child = self._child_pid(rec)
        if child <= 0:
            raise ValueError("task not running")
        os.killpg(os.getpgid(child), signum)

    def task_stats(self, task_id: str) -> dict:
        from .driver import read_proc_stats
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return super().task_stats(task_id)
        child = self._child_pid(rec)
        if child <= 0:
            return super().task_stats(task_id)
        return read_proc_stats(child)

    def _child_pid(self, rec: dict) -> int:
        try:
            with open(rec.get("pidfile", "")) as f:
                parts = f.read().split()
            return int(parts[1]) if len(parts) > 1 else 0
        except (OSError, ValueError, IndexError):
            return 0

    def _kill_task_group(self, rec: dict) -> None:
        child = self._child_pid(rec)
        if child > 0:
            try:
                os.killpg(child, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def destroy_task(self, task_id: str) -> None:
        self.stop_task(task_id, kill_timeout=0.2)
        with self._lock:
            self._tasks.pop(task_id, None)

    def inspect_task(self, task_id: str) -> Optional[TaskHandle]:
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return None
        return TaskHandle(task_id=task_id, driver=self.name, pid=rec["pid"],
                          config={"result": rec["result"]})

    def recover_task(self, handle: TaskHandle) -> bool:
        """Reattach: the executor (or at least its result file) carries the
        task's fate across client restarts."""
        result_path = handle.config.get("result", "")
        rec = {"pid": handle.pid, "proc": None, "result": result_path,
               "pidfile": handle.config.get("pidfile", "")}
        if self._read_result(result_path) is not None or \
           self._executor_alive(rec):
            with self._lock:
                self._tasks[handle.task_id] = rec
            return True
        return False
