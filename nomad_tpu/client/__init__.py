"""Node agent (ref client/): alloc/task runners, drivers, fingerprinting,
local state persistence + task reattach."""
from .client import Client  # noqa: F401
from .driver import (  # noqa: F401
    BUILTIN_DRIVERS, Driver, ExitResult, MockDriver, RawExecDriver, TaskHandle,
)
from .alloc_runner import AllocRunner  # noqa: F401
from .task_runner import TaskRunner  # noqa: F401
from .fingerprint import fingerprint_node  # noqa: F401
from .state_db import StateDB  # noqa: F401
from .taskenv import build_task_env, interpolate  # noqa: F401
