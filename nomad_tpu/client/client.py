"""Client / node agent (ref client/client.go:325 NewClient, run:1710,
registerAndHeartbeat:1584, watchAllocations:2033, runAllocs:2263,
restoreState:1090).

Talks to the server through an RPC interface (in-process for -dev mode,
HTTP otherwise): node_register / node_heartbeat / node_update_status /
node_get_client_allocs / node_update_allocs.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

from .. import chrono
from ..metrics import metrics, record_swallowed_error
from ..structs import (
    Allocation, Node, ALLOC_DESIRED_STOP, NODE_STATUS_DOWN,
    NODE_STATUS_INIT, NODE_STATUS_READY, new_id,
)
from .alloc_runner import AllocRunner
from .driver import BUILTIN_DRIVERS, Driver
from .fingerprint import fingerprint_drivers, fingerprint_node
from .state_db import StateDB


class Client:
    def __init__(self, rpc, data_dir: str, datacenter: str = "dc1",
                 node_class: str = "", name: str = "",
                 drivers: Optional[dict[str, Driver]] = None,
                 logger=None, plugin_dir: str = "",
                 clock: Optional[chrono.Clock] = None, seed: int = 0):
        self.rpc = rpc
        self.data_dir = data_dir
        # heartbeat bookkeeping and retry jitter ride the injectable
        # clock (ISSUE 18): partition sims time-compress the whole
        # disconnect/reconnect cycle on a ManualClock; `seed` makes the
        # retry jitter stream reproducible
        self._clock = clock or chrono.REAL
        self._hb_rng = random.Random(f"client-hb:{seed}:{name}")
        self.alloc_dir_root = os.path.join(data_dir, "allocs")
        self.logger = logger or (lambda msg: None)
        os.makedirs(self.alloc_dir_root, exist_ok=True)

        self.state_db = StateDB(os.path.join(data_dir, "client_state.db"))
        self.drivers: dict[str, Driver] = drivers if drivers is not None \
            else {name: cls() for name, cls in BUILTIN_DRIVERS.items()}
        # external plugins (ref client config plugin_dir + go-plugin
        # Discover): subprocess drivers join the same registry; CSI
        # plugins register with the csimanager below once it exists
        if plugin_dir:
            from .plugin_host import discover_all
            found = discover_all(plugin_dir, self.logger)
            self.plugin_drivers = found["driver"]
            self._plugin_csi = found["csi"]
            self.drivers.update(self.plugin_drivers)
        else:
            self.plugin_drivers = {}
            self._plugin_csi = {}
        for d in self.drivers.values():
            # catalog access (connect proxy); ext drivers are duck-typed
            bind = getattr(d, "bind_client", None)
            if bind is not None:
                bind(self)

        from .csimanager import CSIManager
        self.csi_manager = CSIManager(self)
        for plug_id, plug in self._plugin_csi.items():
            # discovered subprocess CSI plugins (ref plugins/csi/client.go:
            # external processes behind the node/controller contract); the
            # node fingerprint picks them up below
            self.csi_manager.register_plugin(
                plug_id, plug, controller=plug.requires_controller)
        from .devicemanager import DeviceManager
        self.device_manager = DeviceManager(self)
        # shared bridge-network hook: one IP allocator + one nomad bridge
        # per client (ref client/allocrunner/networkmanager_linux.go)
        from .network_hook import NetworkHook
        self.network_hook = NetworkHook(logger=self.logger)

        node_id = self.state_db.get_node_id()
        self.node: Node = fingerprint_node(data_dir, datacenter, node_class,
                                           name, node_id)
        self.state_db.put_node_id(self.node.id)
        self.node.drivers = fingerprint_drivers(self.drivers)
        for dname, info in self.node.drivers.items():
            if info.detected:
                self.node.attributes[f"driver.{dname}"] = "1"
        if self._plugin_csi:
            self.node.csi_node_plugins = self.csi_manager.fingerprint()
            self.node.csi_controller_plugins = \
                self.csi_manager.fingerprint_controllers()
        self.node.status = NODE_STATUS_INIT
        self.node.compute_class()

        # GC knobs (ref client/config gc_interval, gc_disk_usage_threshold,
        # gc_max_allocs)
        self.gc_interval_sec = 60.0
        # template watch cadence (consul-template's re-render loop analog)
        self.template_interval_sec = 2.0
        self.gc_max_allocs = 50
        self.gc_disk_usage_threshold = 80.0

        self._lock = threading.Lock()
        self.alloc_runners: dict[str, AllocRunner] = {}
        self._alloc_versions: dict[str, int] = {}   # alloc_id -> modify_index
        self._last_alloc_index = 0
        self._heartbeat_ttl = 10.0
        # heartbeat-stop (ref client/heartbeatstop.go): allocs whose TG
        # sets stop_after_client_disconnect are stopped LOCALLY when the
        # client has been unable to heartbeat for that long — the client
        # half of the server-side lost-alloc handling
        # (reconcile_util.delay_by_stop_after_client_disconnect)
        self._last_heartbeat_ok = self._clock.monotonic()
        self._shutdown = threading.Event()
        # consecutive _watch_allocations failures; >0 marks a suspected
        # partition, and the first successful poll after one triggers a
        # full reconcile against the server's view (ISSUE 18)
        self._watch_failures = 0
        self._dirty_allocs: set[str] = set()
        self._dirty_cond = threading.Condition()
        self._exec_sessions: dict[str, list] = {}  # sid -> [session, last]
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._restore_state()
        self._register()
        for target, name in ((self._heartbeat_loop, "client-heartbeat"),
                             (self._watch_allocations, "client-watch-allocs"),
                             (self._sync_allocs_loop, "client-alloc-sync"),
                             (self._heartbeat_stop_loop,
                              "client-heartbeat-stop"),
                             (self._gc_loop, "client-gc"),
                             (self._stats_loop, "client-task-stats")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    # stats hook cadence (ref taskrunner/stats_hook.go driving
    # DriverStats at the telemetry collection interval)
    stats_interval_sec = 1.0

    def _stats_loop(self) -> None:
        """Periodic per-task usage sampling (ref
        client/allocrunner/taskrunner/stats_hook.go + setGaugeForMemory/
        CpuStats in client.go:2600 emitStats): every running task's
        cpu/rss is pulled from its driver and published as gauges keyed
        by job/group/task — never by alloc id, which would grow metric
        cardinality without bound. The on-demand alloc_stats API keeps
        serving point-in-time reads independently of this loop."""
        from ..metrics import metrics
        published: set[tuple] = set()
        while not self._shutdown.wait(self.stats_interval_sec):
            try:
                with self._lock:
                    runners = list(self.alloc_runners.values())
                rollup: dict[tuple, tuple] = {}
                for ar in runners:
                    alloc = ar.alloc
                    # snapshot under the runner's own lock: task starts
                    # mutate the dict concurrently, and an unguarded
                    # iteration error would kill this daemon thread
                    with ar._lock:
                        task_runners = dict(ar.task_runners)
                    for name, tr in task_runners.items():
                        try:
                            st = tr.stats()
                        except Exception:  # noqa: BLE001 — mid-stop
                            continue
                        key = (alloc.job_id, alloc.task_group, name)
                        cpu, rss = rollup.get(key, (0.0, 0))
                        rollup[key] = (cpu + st.get("cpu_percent", 0.0),
                                       rss + st.get("memory_rss_bytes", 0))
                for (job, tg, task), (cpu, rss) in rollup.items():
                    base = f"nomad.client.allocs.{job}.{tg}.{task}"
                    # per-live-task gauges: bounded by tasks on THIS
                    # client, and the retire pass below deletes rows on
                    # churn — cardinality cannot grow without bound
                    # nomadlint: disable=OBS001 — bounded + retired below
                    metrics.set_gauge(f"{base}.cpu_percent", cpu)
                    # nomadlint: disable=OBS001 — bounded + retired below
                    metrics.set_gauge(f"{base}.memory_rss_bytes",
                                      float(rss))
                # retire gauges for tasks that stopped since last cycle:
                # without this, dead jobs report phantom usage forever
                # and job churn grows the gauge set without bound
                for job, tg, task in published - set(rollup):
                    base = f"nomad.client.allocs.{job}.{tg}.{task}"
                    metrics.gauges.pop(f"{base}.cpu_percent", None)
                    metrics.gauges.pop(f"{base}.memory_rss_bytes", None)
                published = set(rollup)
            except Exception as e:      # noqa: BLE001 — sampler survives
                self.logger(f"client: stats sample failed: {e!r}")

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._dirty_cond:
            self._dirty_cond.notify_all()
        with self._lock:
            runners = list(self.alloc_runners.values())
        for ar in runners:
            for tr in list(ar.task_runners.values()):
                tr.kill("client shutting down")
        # kill() only signals; wait for the runner threads to actually
        # stop their drivers so subprocesses and proxy listeners are gone
        # when shutdown returns — a fresh client on this host may be
        # assigned the same dynamic ports immediately. ONE shared
        # deadline: many slow-dying tasks must not serialize into
        # minutes of shutdown
        deadline = time.monotonic() + 5.0
        for ar in runners:
            for tr in list(ar.task_runners.values()):
                try:
                    tr.wait_done(timeout=max(0.0,
                                             deadline - time.monotonic()))
                # shutdown path: a runner that outlives the shared
                # deadline is logged by its own kill path; nothing to do
                except Exception:  # nomadlint: disable=EXC001 — shutdown best-effort
                    pass
        for drv in self.plugin_drivers.values():
            drv.shutdown()
        for plug in self._plugin_csi.values():
            plug.shutdown()

    # ---------------------------------------------------------- registration

    def _register(self) -> None:
        """ref client.go:1584 registerAndHeartbeat (register half)"""
        while not self._shutdown.is_set():
            try:
                resp = self.rpc.node_register(self.node)
                self._heartbeat_ttl = resp.get("heartbeat_ttl", 10.0)
                break
            except Exception as e:      # noqa: BLE001
                self.logger(f"client: register failed: {e!r}")
                self._shutdown.wait(1.0)
        try:
            self.rpc.node_update_status(self.node.id, NODE_STATUS_READY)
            self.node.status = NODE_STATUS_READY
        except Exception as e:          # noqa: BLE001
            self.logger(f"client: ready update failed: {e!r}")

    # a failed beat is retried this many times within ONE loop tick,
    # after short seeded jitter — N dropped requests must not cost
    # N * TTL/2 of silence and an invalidation (ISSUE 18)
    HEARTBEAT_RETRIES = 3
    HEARTBEAT_RETRY_JITTER_S = (0.1, 0.5)

    def _heartbeat_once(self) -> bool:
        """One heartbeat with bounded in-tick retries. Returns True when
        a beat landed. Test-drivable without the loop thread."""
        last_exc: Optional[Exception] = None
        for attempt in range(1 + self.HEARTBEAT_RETRIES):
            if self._shutdown.is_set():
                return False
            if attempt:
                metrics.incr("nomad.client.heartbeat_retries")
                lo, hi = self.HEARTBEAT_RETRY_JITTER_S
                self._clock.sleep(lo + (hi - lo) * self._hb_rng.random())
            try:
                resp = self.rpc.node_update_status(self.node.id,
                                                   NODE_STATUS_READY)
                self._heartbeat_ttl = resp.get("heartbeat_ttl",
                                               self._heartbeat_ttl)
                self._last_heartbeat_ok = self._clock.monotonic()
                return True
            except Exception as e:      # noqa: BLE001
                last_exc = e
                self.logger(f"client: heartbeat failed "
                            f"(attempt {attempt + 1}): {e!r}")
        # retries exhausted — re-register OUTSIDE the retry ladder: the
        # server may have GC'd us. A silent re-register failure leaves
        # the node invisibly dead (EXC001) — count + log it; the loop
        # retries next tick
        self.logger(f"client: heartbeat gave up after "
                    f"{1 + self.HEARTBEAT_RETRIES} attempts: {last_exc!r}")
        try:
            self.rpc.node_register(self.node)
            self.rpc.node_update_status(self.node.id, NODE_STATUS_READY)
            self._last_heartbeat_ok = self._clock.monotonic()
            return True
        except Exception as e2:         # noqa: BLE001
            record_swallowed_error("client.heartbeat.reregister",
                                   e2, self.logger)
            return False

    def _heartbeat_loop(self) -> None:
        # heartbeats go through UpdateStatus(ready), not a bare TTL reset,
        # so a node the server marked down transitions back to ready and
        # blocked evals unblock (ref client.go registerAndHeartbeat ->
        # Node.UpdateStatus)
        while not self._shutdown.wait(max(0.2, self._heartbeat_ttl / 2)):
            self._heartbeat_once()

    def _heartbeat_stop_loop(self) -> None:
        """Stop allocs locally after prolonged server disconnection (ref
        client/heartbeatstop.go watch): a TG opting in via
        stop_after_client_disconnect must not keep running on a
        partitioned node past that grace — the server will have replaced
        it, and two live copies of (say) a singleton service is exactly
        what the knob exists to prevent."""
        while not self._shutdown.wait(1.0):
            silence = self._clock.monotonic() - self._last_heartbeat_ok
            if silence <= self._heartbeat_ttl:
                continue
            with self._lock:
                runners = list(self.alloc_runners.values())
            for ar in runners:
                alloc = ar.alloc
                job = alloc.job
                tg = job.lookup_task_group(alloc.task_group) if job else None
                if tg is None or tg.stop_after_client_disconnect_sec is None:
                    continue
                if silence <= tg.stop_after_client_disconnect_sec:
                    continue
                if alloc.terminal_status():
                    continue
                self.logger(
                    f"client: stopping alloc {alloc.id[:8]} after "
                    f"{silence:.0f}s without a successful heartbeat "
                    f"(stop_after_client_disconnect)")
                for tr in list(ar.task_runners.values()):
                    tr.kill("client disconnected from servers")

    # --------------------------------------------------------- alloc watch

    def _watch_allocations(self) -> None:
        """Long-poll the server for alloc changes (ref client.go:2033).

        Reconnect reconciliation (ISSUE 18): after ANY poll failure the
        next contact does a full `_reconcile_allocs()` instead of
        resuming the incremental long-poll — during the outage the
        server may have replaced/stopped allocs at indexes this client
        never saw, and trusting `_last_alloc_index` would silently skip
        them."""
        while not self._shutdown.is_set():
            if self._watch_failures:
                if self._reconcile_allocs():
                    self._watch_failures = 0
                else:
                    self._shutdown.wait(1.0)
                continue
            try:
                resp = self.rpc.node_get_client_allocs(
                    self.node.id, min_index=self._last_alloc_index,
                    timeout=5.0)
            except Exception as e:      # noqa: BLE001
                self.logger(f"client: watch allocs failed: {e!r}")
                self._watch_failures += 1
                self._shutdown.wait(1.0)
                continue
            self._last_alloc_index = max(self._last_alloc_index,
                                         resp.get("index", 0))
            self._run_allocs(resp.get("allocs", {}))

    def _reconcile_allocs(self) -> bool:
        """Resync alloc state against the server's CURRENT view at a
        known index (the heal half of a partition). timeout=0.0 makes
        Node.GetClientAllocs return immediately with the full alloc map
        + the server's index; `_run_allocs` then applies adds/updates
        AND removals, and every surviving alloc is marked dirty so the
        sync loop re-pushes client status the server may have missed.
        Returns True once the resync landed."""
        try:
            resp = self.rpc.node_get_client_allocs(
                self.node.id, min_index=0, timeout=0.0)
        except Exception as e:          # noqa: BLE001
            self.logger(f"client: reconcile failed: {e!r}")
            return False
        index = resp.get("index", 0)
        self._run_allocs(resp.get("allocs", {}))
        # adopt the server's index only AFTER the diff applied: a crash
        # in between re-reconciles rather than skipping the window
        self._last_alloc_index = max(self._last_alloc_index, index)
        with self._lock:
            survivors = list(self.alloc_runners)
        with self._dirty_cond:
            self._dirty_allocs.update(survivors)
            self._dirty_cond.notify_all()
        metrics.incr("nomad.client.reconnect_reconciles")
        self.logger(f"client: reconciled {len(survivors)} allocs at "
                    f"server index {index} after reconnect")
        return True

    def _run_allocs(self, server_allocs: dict[str, int]) -> None:
        """Diff desired vs running (ref client.go:2263 runAllocs)."""
        with self._lock:
            known = dict(self._alloc_versions)
        # removed allocs: server no longer tracks them => destroy
        for alloc_id in set(known) - set(server_allocs):
            self._remove_alloc(alloc_id)
        # new or updated
        for alloc_id, modify_index in server_allocs.items():
            if known.get(alloc_id) == modify_index:
                continue
            try:
                alloc = self.rpc.alloc_get(alloc_id)
            except Exception as e:      # noqa: BLE001
                self.logger(f"client: fetch alloc {alloc_id[:8]}: {e!r}")
                continue
            if alloc is None:
                continue
            with self._lock:
                self._alloc_versions[alloc_id] = modify_index
                existing = self.alloc_runners.get(alloc_id)
            if existing is not None:
                existing.update(alloc)
            elif not alloc.terminal_status():
                self._add_alloc(alloc)

    def _add_alloc(self, alloc: Allocation) -> None:
        ar = AllocRunner(self, alloc)
        with self._lock:
            self.alloc_runners[alloc.id] = ar
        self.state_db.put_allocation(alloc)
        ar.run()

    def _remove_alloc(self, alloc_id: str) -> None:
        with self._lock:
            ar = self.alloc_runners.pop(alloc_id, None)
            self._alloc_versions.pop(alloc_id, None)
        if ar is not None:
            ar.destroy()
        self.state_db.delete_allocation(alloc_id)

    # ----------------------------------------------------------- alloc sync

    def alloc_state_updated(self, ar: AllocRunner) -> None:
        with self._dirty_cond:
            self._dirty_allocs.add(ar.alloc.id)
            self._dirty_cond.notify_all()
        # persist reattach handles on every transition
        self.state_db.put_task_handles(ar.alloc.id, ar.persistable_handles())

    def _sync_allocs_loop(self) -> None:
        """Batched client->server status updates (ref client.go
        allocSync)."""
        while not self._shutdown.is_set():
            with self._dirty_cond:
                if not self._dirty_allocs:
                    self._dirty_cond.wait(0.5)
                dirty = list(self._dirty_allocs)
                self._dirty_allocs.clear()
            # service registration retry (the consul sync-loop analog): a
            # running alloc whose register RPC failed re-attempts each pass
            with self._lock:
                runners = list(self.alloc_runners.values())
            for ar in runners:
                with ar._lock:
                    any_running = any(s.state == "running"
                                      for s in ar.task_states.values())
                if not ar._services_registered and any_running:
                    try:
                        ar._register_services()
                    except Exception as e:      # noqa: BLE001
                        self.logger(f"client: service sync: {e!r}")
            # deployment health is time-based (min_healthy_time elapses with
            # no task-state change), so allocs with an undecided verdict are
            # re-evaluated every pass (ref allocrunner health_hook's timer)
            with self._lock:
                for alloc_id, ar in self.alloc_runners.items():
                    if alloc_id in dirty:
                        continue
                    if ar.alloc.deployment_id and (
                            ar.alloc.deployment_status is None or
                            ar.alloc.deployment_status.healthy is None):
                        dirty.append(alloc_id)
            if not dirty:
                continue
            updates = []
            with self._lock:
                for alloc_id in dirty:
                    ar = self.alloc_runners.get(alloc_id)
                    if ar is not None:
                        updates.append(ar.client_alloc())
            if not updates:
                continue
            try:
                self.rpc.node_update_allocs(updates)
                # GC eligibility: a terminal status the server has acked
                # (ref client/gc.go — collection waits for server sync)
                with self._lock:
                    for u in updates:
                        if u.client_terminal_status() and \
                                u.id in self.alloc_runners:
                            self.alloc_runners[u.id].synced_terminal = True
            except Exception as e:      # noqa: BLE001
                self.logger(f"client: alloc sync failed: {e!r}")
                with self._dirty_cond:
                    self._dirty_allocs.update(dirty)
                self._shutdown.wait(0.5)

    # -------------------------------------------------------------- restore

    def _restore_state(self) -> None:
        """Reattach to allocs from the local state DB (ref client.go:1090
        restoreState)."""
        for alloc in self.state_db.get_all_allocations():
            if alloc.server_terminal_status():
                self.state_db.delete_allocation(alloc.id)
                continue
            handles = self.state_db.get_task_handles(alloc.id)
            ar = AllocRunner(self, alloc)
            with self._lock:
                self.alloc_runners[alloc.id] = ar
            if handles:
                ar.restore(handles)

    # -------------------------------------------------------------- helpers

    # ------------------------------------------------- client API surface
    # (ref client/alloc_endpoint.go, client/fs_endpoint.go — served over
    # HTTP by the agent, reachable directly or via server proxy)

    def _runner(self, alloc_id: str) -> AllocRunner:
        with self._lock:
            ar = self.alloc_runners.get(alloc_id)
        if ar is None:
            raise KeyError(f"unknown allocation {alloc_id!r}")
        return ar

    def alloc_signal(self, alloc_id: str, task: str = "",
                     sig: str = "SIGUSR1") -> None:
        self._runner(alloc_id).signal(task, sig)

    def alloc_restart(self, alloc_id: str, task: str = "") -> None:
        self._runner(alloc_id).restart_task(task)

    def alloc_stats(self, alloc_id: str) -> dict:
        return self._runner(alloc_id).stats()

    def alloc_namespace(self, alloc_id: str) -> str:
        return self._runner(alloc_id).alloc.namespace

    def _fs_path(self, alloc_id: str, path: str) -> str:
        """Resolve a path inside the alloc dir, refusing escapes (the
        reference's alloc-dir sandboxing, client/allocdir)."""
        root = os.path.realpath(self._runner(alloc_id).alloc_dir)
        full = os.path.realpath(os.path.join(root, path.lstrip("/")))
        if full != root and not full.startswith(root + os.sep):
            raise ValueError("path escapes allocation directory")
        return full

    def fs_list(self, alloc_id: str, path: str = "/") -> list[dict]:
        """ref client/fs_endpoint.go List"""
        full = self._fs_path(alloc_id, path)
        out = []
        for name in sorted(os.listdir(full)):
            st = os.stat(os.path.join(full, name))
            out.append({
                "Name": name,
                "IsDir": os.path.isdir(os.path.join(full, name)),
                "Size": st.st_size,
                "FileMode": oct(st.st_mode & 0o7777),
                "ModTime": st.st_mtime,
            })
        return out

    def fs_stat(self, alloc_id: str, path: str) -> dict:
        full = self._fs_path(alloc_id, path)
        st = os.stat(full)
        return {
            "Name": os.path.basename(full) or "/",
            "IsDir": os.path.isdir(full),
            "Size": st.st_size,
            "FileMode": oct(st.st_mode & 0o7777),
            "ModTime": st.st_mtime,
        }

    def fs_read(self, alloc_id: str, path: str, offset: int = 0,
                limit: int = -1) -> bytes:
        """ref fs_endpoint.go Cat/ReadAt"""
        full = self._fs_path(alloc_id, path)
        with open(full, "rb") as f:
            if offset:
                f.seek(offset)
            return f.read(limit if limit >= 0 else -1)

    # ------------------------------------------------------- exec streams

    def alloc_exec_start(self, alloc_id: str, task: str, command: list,
                         tty: bool = False) -> str:
        """Open an interactive exec session inside a running task (ref
        client/alloc_endpoint.go exec + drivers ExecTaskStreaming).
        Returns a session id for the stdin/output/close calls."""
        ar = self._runner(alloc_id)
        tr = ar.task_runners.get(task)
        if tr is None or tr.handle is None:
            raise ValueError(f"task {task!r} is not running")
        session = tr.driver.exec_task(
            tr.handle.task_id, list(command), tty=tty,
            cwd=tr.task_dir, env=tr.env)
        sid = new_id()
        with self._lock:
            self._exec_sessions[sid] = [session, time.monotonic()]
        return sid

    def _exec_session(self, sid: str):
        with self._lock:
            entry = self._exec_sessions.get(sid)
            if entry is None:
                raise KeyError(f"unknown exec session {sid!r}")
            entry[1] = time.monotonic()      # any touch counts as activity
            return entry[0]

    def alloc_exec_stdin(self, sid: str, data: bytes) -> None:
        self._exec_session(sid).write_stdin(data)

    def alloc_exec_stdin_close(self, sid: str) -> None:
        """EOF the session's stdin (stdin-consuming commands like `cat`
        terminate on it; ref exec streaming close of the stdin frame)."""
        self._exec_session(sid).close_stdin()

    def alloc_exec_output(self, sid: str, wait: float = 1.0) -> dict:
        return self._exec_session(sid).read_output(wait=min(wait, 30.0))

    def alloc_exec_resize(self, sid: str, rows: int, cols: int) -> None:
        self._exec_session(sid).resize(rows, cols)

    def alloc_exec_close(self, sid: str) -> None:
        with self._lock:
            entry = self._exec_sessions.pop(sid, None)
        if entry is not None:
            entry[0].terminate()

    def _reap_exec_sessions(self) -> None:
        """Abandoned sessions are terminated by the GC tick. Idle is
        measured from LAST ACTIVITY (any stdin/output/resize touch), so
        a polling client never loses the tail output of a long command
        and an active interactive shell is never reaped."""
        now = time.monotonic()
        with self._lock:
            stale = [sid for sid, (s, last) in self._exec_sessions.items()
                     if (s.exit_code is not None and now - last > 300)
                     or now - last > 3600]
            for sid in stale:
                s, _ = self._exec_sessions.pop(sid)
                s.terminate()

    def fs_logs_follow(self, alloc_id: str, task: str,
                       log_type: str = "stdout", offset: int = 0,
                       wait: float = 10.0) -> tuple[bytes, int]:
        """Long-poll tail of a task log (ref fs_endpoint.go Logs with
        follow=true): blocks until bytes exist past `offset` or the wait
        expires; returns (data, next_offset)."""
        deadline = time.monotonic() + min(wait, 30.0)
        while True:
            # logmon copy-truncates on rotation: a shrunken file means
            # our offset points past EOF of the NEW file — restart from
            # its beginning instead of polling empty reads forever
            try:
                st = self.fs_stat(alloc_id, f"{task}/{task}.{log_type}.log")
                if int(st.get("Size", 0)) < offset:
                    offset = 0
            except (ValueError, OSError, KeyError):
                pass
            data = self.fs_logs(alloc_id, task, log_type, offset, "start",
                                -1)
            if data or time.monotonic() >= deadline:
                return data, offset + len(data)
            # local log-tail poll cadence (fs_stat reads the local disk),
            # not an RPC retry backoff
            time.sleep(0.1)  # nomadlint: disable=RPC001 — log-follow poll, no transport involved

    def fs_logs(self, alloc_id: str, task: str, log_type: str = "stdout",
                offset: int = 0, origin: str = "start",
                limit: int = -1) -> bytes:
        """Task log access (ref fs_endpoint.go Logs). Logs live at
        <alloc>/<task>/<task>.<type>.log (driver log convention)."""
        if log_type not in ("stdout", "stderr"):
            raise ValueError("type must be stdout or stderr")
        ar = self._runner(alloc_id)
        alloc = ar.alloc
        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job \
            else None
        if tg is None or tg.lookup_task(task) is None:
            raise ValueError(f"unknown task {task!r} in allocation")
        path = f"{task}/{task}.{log_type}.log"
        full = self._fs_path(alloc_id, path)
        if not os.path.exists(full):
            return b""
        size = os.path.getsize(full)
        with open(full, "rb") as f:
            if origin == "end":
                # offset counts back from EOF (ref api/fs.go Logs origin)
                f.seek(max(0, size - offset) if offset else
                       (max(0, size - limit) if limit >= 0 else 0))
            elif offset:
                f.seek(offset)
            return f.read(limit if limit >= 0 else -1)

    def host_stats(self) -> dict:
        """ref client/stats/host.go HostStats"""
        # nomadlint: disable=DET001 — capture timestamp, not a decision
        stats = {"Timestamp": time.time(), "CPUTicksConsumed": 0.0}
        try:
            load1, load5, load15 = os.getloadavg()
            stats["CPU"] = [{"CPU": "cpu-total", "Total": load1 * 100}]
            stats["LoadAvg"] = [load1, load5, load15]
        except OSError:
            pass
        try:
            meminfo = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    meminfo[k] = int(v.split()[0]) * 1024
            stats["Memory"] = {
                "Total": meminfo.get("MemTotal", 0),
                "Available": meminfo.get("MemAvailable", 0),
                "Free": meminfo.get("MemFree", 0),
                "Used": meminfo.get("MemTotal", 0)
                - meminfo.get("MemAvailable", 0),
            }
        except OSError:
            pass
        try:
            st = os.statvfs(self.data_dir)
            stats["DiskStats"] = [{
                "Device": self.data_dir,
                "Size": st.f_blocks * st.f_frsize,
                "Available": st.f_bavail * st.f_frsize,
                "UsedPercent": 100.0 * (1 - st.f_bavail / st.f_blocks)
                if st.f_blocks else 0.0,
            }]
        except OSError:
            pass
        stats["AllocDirStats"] = {"Allocs": self.num_allocs()}
        stats["DeviceStats"] = self.device_manager.all_stats()
        stats["Uptime"] = time.monotonic()
        return stats

    def _gc_loop(self) -> None:
        """Disk-pressure / alloc-count driven GC (ref client/gc.go
        AllocGarbageCollector.run: checks every interval, evicts oldest
        terminal allocs while above thresholds)."""
        while not self._shutdown.wait(self.gc_interval_sec):
            try:
                self._gc_check()
                self._reap_exec_sessions()
                # the client half of the volume watcher's detach machine
                self.csi_manager.reconcile_claims()
            except Exception as e:      # noqa: BLE001
                self.logger(f"client: gc pass failed: {e!r}")

    def _gc_check(self) -> None:
        with self._lock:
            runners = dict(self.alloc_runners)
        terminal = sorted(
            (ar for ar in runners.values()
             if ar.alloc.terminal_status() or ar.synced_terminal),
            key=lambda ar: ar.alloc.modify_index)  # oldest first
        if not terminal:
            return
        over_count = len(runners) > self.gc_max_allocs

        def disk_pressure() -> bool:
            try:
                st = os.statvfs(self.alloc_dir_root)
            except OSError:
                return False
            if not st.f_blocks:
                return False
            used = 100.0 * (1 - st.f_bavail / st.f_blocks)
            return used >= self.gc_disk_usage_threshold
        for ar in terminal:
            if not over_count and not disk_pressure():
                return
            try:
                self.gc_alloc(ar.alloc.id)
                self.logger(f"client: gc'd alloc {ar.alloc.id[:8]}")
            except (KeyError, ValueError):
                pass
            with self._lock:
                over_count = len(self.alloc_runners) > self.gc_max_allocs

    def gc_alloc(self, alloc_id: str) -> None:
        """Destroy one terminal alloc and remove its dir (ref
        client/gc.go Collect)."""
        import shutil
        ar = self._runner(alloc_id)
        # eligible once the SERVER knows it's over: either the server marked
        # it terminal (our stored copy reflects server desired/client state)
        # or we've successfully synced a terminal client status. A merely
        # is_done() runner whose status hasn't synced yet would be re-added
        # by the next alloc-watch pass after GC.
        if not (ar.alloc.terminal_status() or ar.synced_terminal):
            raise ValueError(f"allocation {alloc_id!r} is not terminal")
        ar.destroy()
        # wait for task processes to actually exit before deleting their
        # dirs (ref client/allocrunner destroy channel)
        for tr in list(ar.task_runners.values()):
            tr.wait_done(timeout=tr.task.kill_timeout_sec + 5.0)
        with self._lock:
            self.alloc_runners.pop(alloc_id, None)
            self._alloc_versions.pop(alloc_id, None)
        self.state_db.delete_allocation(alloc_id)
        shutil.rmtree(ar.alloc_dir, ignore_errors=True)

    def gc_all(self) -> int:
        """Destroy all terminal allocs (ref client/gc.go CollectAll)."""
        with self._lock:
            candidates = [aid for aid, ar in self.alloc_runners.items()
                          if ar.alloc.terminal_status() or ar.synced_terminal]
        n = 0
        for aid in candidates:
            try:
                self.gc_alloc(aid)
                n += 1
            except (KeyError, ValueError):
                pass
        return n

    def register_device_plugin(self, plugin) -> None:
        """Attach a device plugin and refresh the node's device inventory
        (ref client/devicemanager fingerprint -> updateNodeFromDevices)."""
        self.device_manager.register_plugin(plugin)
        self.node.node_resources.devices = self.device_manager.fingerprint()
        try:
            self.rpc.node_register(self.node)
        except Exception as e:          # noqa: BLE001
            self.logger(f"client: device fingerprint update failed: {e!r}")

    def register_csi_plugin(self, plugin_id: str, plugin,
                            controller: bool = False) -> None:
        """Attach a CSI node (and optionally controller) plugin and
        refresh the node fingerprint (ref client/pluginmanager/csimanager
        fingerprint loop)."""
        self.csi_manager.register_plugin(plugin_id, plugin,
                                         controller=controller)
        self.node.csi_node_plugins = self.csi_manager.fingerprint()
        self.node.csi_controller_plugins = \
            self.csi_manager.fingerprint_controllers()
        try:
            self.rpc.node_register(self.node)
        except Exception as e:          # noqa: BLE001
            self.logger(f"client: csi fingerprint update failed: {e!r}")

    def get_driver(self, name: str) -> Driver:
        driver = self.drivers.get(name)
        if driver is None:
            raise ValueError(f"driver {name!r} not available")
        return driver

    def num_allocs(self) -> int:
        with self._lock:
            return len(self.alloc_runners)
