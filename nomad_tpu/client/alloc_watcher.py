"""Previous-allocation watcher + ephemeral disk migration (ref
client/allocwatcher/alloc_watcher.go: NewAllocWatcher, localPrevAlloc,
remotePrevAlloc).

When a replacement alloc lands with `previous_allocation` set and its task
group asks for sticky/migrated ephemeral disk, the runner blocks until the
previous alloc is terminal, then moves (local) or downloads (remote, over
the previous node's HTTP fs API) each task's `local/` dir and the alloc
`data/` dir into the new alloc dir.
"""
from __future__ import annotations

import json
import os
import shutil
import time
import urllib.parse
import urllib.request
from typing import Optional


class PrevAllocWatcher:
    """ref allocwatcher.NewAllocWatcher — picks local vs remote strategy."""

    def __init__(self, client, alloc, logger=None):
        self.client = client
        self.alloc = alloc
        self.logger = logger or (lambda msg: None)
        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job \
            else None
        disk = tg.ephemeral_disk if tg else None
        self.migrate = bool(disk and disk.migrate)
        self.sticky = bool(disk and (disk.sticky or disk.migrate))

    def wait_and_migrate(self, timeout: float = 300.0) -> bool:
        """Block until the previous alloc terminates, then migrate its data.
        Returns True if data was migrated."""
        prev_id = self.alloc.previous_allocation
        if not prev_id or not self.sticky:
            return False
        prev_runner = self.client.alloc_runners.get(prev_id)
        if prev_runner is not None:
            return self._local(prev_runner, timeout)
        # runner already reaped (the server stops advertising terminal
        # allocs) but the alloc dir may still be on this node's disk —
        # migrate straight from it
        prev_dir = os.path.join(self.client.alloc_dir_root, prev_id)
        if os.path.isdir(prev_dir):
            return self._move_dirs(prev_dir)
        if self.migrate:
            return self._remote(prev_id, timeout)
        return False

    # ---------------------------------------------------------------- local

    def _local(self, prev_runner, timeout: float) -> bool:
        """ref allocwatcher localPrevAlloc: same node — wait + move dirs."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if prev_runner.is_done() or prev_runner.alloc.terminal_status():
                break
            time.sleep(0.1)
        else:
            self.logger(f"allocwatcher: timed out waiting on {prev_runner.alloc.id}")
            return False
        return self._move_dirs(prev_runner.alloc_dir)

    def _move_dirs(self, src_root: str) -> bool:
        dst_root = os.path.join(self.client.alloc_dir_root, self.alloc.id)
        moved = False
        for rel in self._migratable_dirs():
            src = os.path.join(src_root, rel)
            if not os.path.isdir(src):
                continue
            dst = os.path.join(dst_root, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.rmtree(dst, ignore_errors=True)
            shutil.move(src, dst)
            moved = True
        return moved

    # --------------------------------------------------------------- remote

    def _remote(self, prev_id: str, timeout: float) -> bool:
        """ref allocwatcher remotePrevAlloc: previous alloc ran on another
        node — poll the servers for its terminal state, then walk the old
        node's /v1/client/fs API and download."""
        deadline = time.time() + timeout
        prev = node_addr = None
        while time.time() < deadline:
            try:
                prev = self.client.rpc.alloc_get(prev_id)
            except Exception:       # noqa: BLE001 — server may be slow
                prev = None
            if prev is not None and prev.terminal_status():
                break
            time.sleep(0.5)
        if prev is None or not prev.terminal_status():
            # never migrate from a still-running alloc (torn reads)
            self.logger(f"allocwatcher: prev {prev_id[:8]} not terminal")
            return False
        node_addr = self._node_http_addr(prev.node_id)
        if not node_addr:
            self.logger(f"allocwatcher: no HTTP addr for node {prev.node_id}")
            return False
        dst_root = os.path.join(self.client.alloc_dir_root, self.alloc.id)
        moved = False
        for rel in self._migratable_dirs():
            if self._download_tree(node_addr, prev_id, rel, dst_root):
                moved = True
        return moved

    def _node_http_addr(self, node_id: str) -> str:
        getter = getattr(self.client.rpc, "node_get_http_addr", None)
        if getter is not None:
            try:
                return getter(node_id) or ""
            except Exception:       # noqa: BLE001
                return ""
        return ""

    def _download_tree(self, base: str, alloc_id: str, rel: str,
                       dst_root: str) -> bool:
        """Recursively fetch one directory via /v1/client/fs/{ls,cat}."""
        try:
            entries = self._http_json(
                base, f"/v1/client/fs/ls/{alloc_id}?path="
                + urllib.parse.quote(rel))
        except OSError:
            return False
        got = False
        for e in entries:
            sub = f"{rel}/{e['Name']}"
            if e.get("IsDir"):
                if self._download_tree(base, alloc_id, sub, dst_root):
                    got = True
                continue
            dst = os.path.join(dst_root, sub)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            try:
                data = self._http_raw(
                    base, f"/v1/client/fs/cat/{alloc_id}?path="
                    + urllib.parse.quote(sub))
            except OSError:
                continue
            with open(dst, "wb") as f:
                f.write(data)
            got = True
        return got

    def _http_json(self, base: str, path: str):
        return json.loads(self._http_raw(base, path) or b"null")

    def _http_raw(self, base: str, path: str) -> bytes:
        if not base.startswith("http"):
            base = "http://" + base
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            return resp.read()

    # ---------------------------------------------------------------- misc

    def _migratable_dirs(self) -> list[str]:
        """Task local/ dirs + the shared alloc data dir (ref
        client/allocdir: SharedAllocDir data/, TaskLocal)."""
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job else None
        rels = ["data"]
        if tg:
            rels += [os.path.join(t.name, "local") for t in tg.tasks]
        return rels
