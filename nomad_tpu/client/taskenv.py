"""Task environment construction (ref client/taskenv/env.go): the NOMAD_*
variables and ${...} interpolation tasks see."""
from __future__ import annotations

import re

from ..structs import Allocation, Node, Task, alloc_name_index


def build_task_env(alloc: Allocation, task: Task, node: Node,
                   task_dir: str, alloc_dir: str, secrets_dir: str,
                   network_status: dict = None) -> dict[str, str]:
    env: dict[str, str] = {}
    job = alloc.job
    env["NOMAD_ALLOC_ID"] = alloc.id
    if network_status:
        # bridge-mode netns (ref network_hook.go: the alloc's network
        # status feeds NOMAD_ALLOC_IP and friends)
        env["NOMAD_ALLOC_IP"] = network_status.get("ip", "")
        env["NOMAD_ALLOC_NETNS"] = network_status.get("netns", "")
    env["NOMAD_SHORT_ALLOC_ID"] = alloc.id[:8]
    env["NOMAD_ALLOC_NAME"] = alloc.name
    env["NOMAD_ALLOC_INDEX"] = str(max(0, alloc_name_index(alloc.name)))
    env["NOMAD_TASK_NAME"] = task.name
    env["NOMAD_GROUP_NAME"] = alloc.task_group
    env["NOMAD_JOB_ID"] = alloc.job_id
    env["NOMAD_JOB_NAME"] = job.name if job else alloc.job_id
    env["NOMAD_JOB_PARENT_ID"] = job.parent_id if job else ""
    env["NOMAD_NAMESPACE"] = alloc.namespace
    env["NOMAD_REGION"] = job.region if job else "global"
    env["NOMAD_DC"] = node.datacenter
    env["NOMAD_ALLOC_DIR"] = alloc_dir
    env["NOMAD_TASK_DIR"] = task_dir
    env["NOMAD_SECRETS_DIR"] = secrets_dir
    env["NOMAD_CPU_LIMIT"] = str(task.resources.cpu)
    env["NOMAD_MEMORY_LIMIT"] = str(task.resources.memory_mb)
    if task.resources.memory_max_mb:
        env["NOMAD_MEMORY_MAX_LIMIT"] = str(task.resources.memory_max_mb)

    # ports: NOMAD_PORT_<label>, NOMAD_ADDR_<label>, NOMAD_HOST_PORT_<label>
    tr = alloc.allocated_resources.tasks.get(task.name)
    networks = list(tr.networks) if tr else []
    networks += list(alloc.allocated_resources.shared.networks)
    for net in networks:
        for p in list(net.reserved_ports) + list(net.dynamic_ports):
            label = _env_key(p.label)
            env[f"NOMAD_PORT_{label}"] = str(p.to or p.value)
            env[f"NOMAD_HOST_PORT_{label}"] = str(p.value)
            if net.ip:
                env[f"NOMAD_ADDR_{label}"] = f"{net.ip}:{p.value}"
                env[f"NOMAD_IP_{label}"] = net.ip

    for k, v in (job.meta if job else {}).items():
        env[f"NOMAD_META_{_env_key(k)}"] = v
    if job:
        tg = job.lookup_task_group(alloc.task_group)
        if tg:
            for k, v in tg.meta.items():
                env[f"NOMAD_META_{_env_key(k)}"] = v
    for k, v in task.meta.items():
        env[f"NOMAD_META_{_env_key(k)}"] = v

    # user env last (may reference NOMAD_* via ${...})
    for k, v in task.env.items():
        env[k] = interpolate(v, env, node)
    return env


_KEY_RE = re.compile(r"[^A-Za-z0-9_]")


def _env_key(k: str) -> str:
    return _KEY_RE.sub("_", k)


_INTERP_RE = re.compile(r"\$\{([^}]+)\}")


def interpolate(value: str, env: dict[str, str], node: Node) -> str:
    """${env.X} / ${NOMAD_*} / ${attr.*} / ${meta.*} / ${node.*}
    interpolation (ref client/taskenv ReplaceEnv)."""

    def repl(m: re.Match) -> str:
        key = m.group(1).strip()
        if key.startswith("env."):
            return env.get(key[4:], "")
        if key in env:
            return env[key]
        if key.startswith("attr."):
            return str(node.attributes.get(key[5:], ""))
        if key.startswith("meta."):
            return str(node.meta.get(key[5:], ""))
        if key == "node.unique.id":
            return node.id
        if key == "node.unique.name":
            return node.name
        if key == "node.datacenter":
            return node.datacenter
        if key == "node.class":
            return node.node_class
        return m.group(0)

    return _INTERP_RE.sub(repl, value)
