"""Out-of-process driver plugin fabric (ref plugins/base/proto/base.proto,
hashicorp/go-plugin): third-party task drivers run as SEPARATE PROCESSES
speaking a socket RPC, so a crashing or misbehaving driver cannot take the
client agent down, and drivers can be written/shipped independently.

Protocol (the go-plugin handshake, re-designed for a zero-dependency
stack):
  1. The host launches the plugin executable with NOMAD_TPU_PLUGIN_MAGIC
     in its environment (plugins refuse to run standalone without it, ref
     go-plugin's magic cookie).
  2. The plugin binds a unix socket and prints ONE handshake line on
     stdout: ``NOMAD_TPU_PLUGIN|<proto-versions>|<socket-path>`` where
     proto-versions is a comma list of protocol versions it speaks.
  3. The host picks the highest common version (negotiation, ref
     base.proto NegotiatedVersion) and connects.
  4. RPC: length-prefixed JSON frames {"id", "method", "params"} ->
     {"id", "result"} | {"id", "error"}. Driver structs cross the wire in
     API shape (api_codec), exactly like the reference's protobuf DTOs.
  5. PluginInfo / Fingerprint / the DriverPlugin method family dispatch
     to the plugin author's Driver subclass (plugin_runtime.serve_driver).

The host wraps each plugin in ExternalDriver, which implements the same
in-process Driver interface the schedulers already use — callers cannot
tell a subprocess driver from a built-in.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import threading
import time
from typing import Optional

from ..structs import DriverInfo
from .driver import Driver, ExitResult, TaskHandle

MAGIC_ENV = "NOMAD_TPU_PLUGIN_MAGIC"
MAGIC_VALUE = "nomad-tpu-driver-plugin-v1"
HANDSHAKE_PREFIX = "NOMAD_TPU_PLUGIN|"
SUPPORTED_PROTOCOLS = (1,)


class PluginError(Exception):
    pass


def _send_frame(sock: socket.socket, obj: dict) -> None:
    raw = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(struct.pack(">I", len(raw)) + raw)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    if n > 64 * 1024 * 1024:
        raise PluginError(f"oversized plugin frame ({n} bytes)")
    raw = b""
    while len(raw) < n:
        chunk = sock.recv(n - len(raw))
        if not chunk:
            return None
        raw += chunk
    return json.loads(raw.decode())


class PluginProcess:
    """One plugin subprocess + its socket transport: launch, handshake,
    version negotiation, framed RPC. Typed wrappers (ExternalDriver,
    ExternalCSIPlugin) overlay the in-process interface on `_call`.
    `plugin_type` pins the expected PluginInfo type; None accepts any
    (generic discovery probes, which adopt() into a typed wrapper)."""

    plugin_type: Optional[str] = None

    def __init__(self, command: list[str], logger=None,
                 start_timeout: float = 10.0):
        self.command = list(command)
        self.logger = logger or (lambda msg: None)
        self.start_timeout = start_timeout
        self._lock = threading.Lock()
        self._relaunch_lock = threading.Lock()
        self._seq = 0
        self.proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None
        self.protocol_version = 0
        self.info: dict = {}
        self.name = os.path.basename(command[0])
        self._launch()

    @classmethod
    def adopt(cls, probe: "PluginProcess") -> "PluginProcess":
        """Rebind a generically-probed live process under a typed
        wrapper (the wrappers add no launch-time state of their own)."""
        if cls.plugin_type and probe.info.get("type") != cls.plugin_type:
            raise PluginError(
                f"plugin {probe.name!r} is {probe.info.get('type')!r}, "
                f"not {cls.plugin_type!r}")
        obj = object.__new__(cls)
        obj.__dict__.update(probe.__dict__)
        return obj

    # ----------------------------------------------------------- lifecycle

    def _launch(self) -> None:
        env = dict(os.environ)
        env[MAGIC_ENV] = MAGIC_VALUE
        self.proc = subprocess.Popen(
            self.command, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, start_new_session=True)
        # ANY failure below must reap the subprocess — discover_plugins
        # logs and continues, and an orphaned plugin would outlive the
        # agent otherwise
        try:
            line = self._read_handshake()
            if not line.startswith(HANDSHAKE_PREFIX):
                raise PluginError(f"bad plugin handshake: {line!r}")
            try:
                _, versions, sock_path = line.split("|", 2)
                offered = {int(v) for v in versions.split(",") if v}
            except ValueError as e:
                raise PluginError(f"malformed handshake {line!r}") from e
            common = offered & set(SUPPORTED_PROTOCOLS)
            if not common:
                raise PluginError(
                    f"no common protocol version (plugin offers "
                    f"{sorted(offered)}, host speaks "
                    f"{list(SUPPORTED_PROTOCOLS)})")
            self.protocol_version = max(common)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(30.0)
            self._sock.connect(sock_path)
            self.sock_path = sock_path
            # PluginInfo exchange (ref base.proto PluginInfo)
            self.info = self._call("PluginInfo")
            if self.plugin_type and \
                    self.info.get("type") != self.plugin_type:
                raise PluginError(
                    f"not a {self.plugin_type} plugin: {self.info}")
            self.name = self.info.get("name", self.name)
        except BaseException:
            self.shutdown()
            raise

    def _read_handshake(self) -> str:
        """One stdout line within start_timeout: select-bounded so a
        silent-but-alive executable can't hang the agent, and process
        death (EOF) fails fast instead of spinning."""
        import select
        fd = self.proc.stdout
        buf = b""
        deadline = time.monotonic() + self.start_timeout
        while time.monotonic() < deadline:
            ready, _, _ = select.select([fd], [], [],
                                        max(0.05, deadline -
                                            time.monotonic()))
            if not ready:
                continue
            chunk = os.read(fd.fileno(), 4096)
            if not chunk:
                raise PluginError("plugin exited before handshake")
            buf += chunk
            if b"\n" in buf:
                return buf.split(b"\n", 1)[0].decode(errors="replace").strip()
        raise PluginError(
            f"no handshake within {self.start_timeout}s")

    def shutdown(self) -> None:
        if self._sock is not None:
            try:
                self._call("Shutdown")
            # polite-shutdown RPC to a possibly-dead plugin; terminate()
            # below is the enforcement path either way
            except Exception:  # nomadlint: disable=EXC001 — best-effort RPC
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    # ----------------------------------------------------------- transport

    def _call(self, method: str, **params):
        with self._lock:
            if self._sock is None:
                raise PluginError(f"plugin {self.name!r} not connected")
            self._seq += 1
            seq = self._seq
            try:
                _send_frame(self._sock, {"id": seq, "method": method,
                                         "params": params})
                # drain until OUR reply: a stale frame (from an earlier
                # timed-out call) must not be mis-delivered
                while True:
                    resp = _recv_frame(self._sock)
                    if resp is None or resp.get("id") == seq:
                        break
            except (socket.timeout, TimeoutError) as e:
                # the stream is now desynchronized (our reply may arrive
                # later): drop the connection rather than misattribute it
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                raise PluginError(
                    f"plugin {self.name!r} rpc {method} timed out") from e
        if resp is None:
            raise PluginError(f"plugin {self.name!r} closed the connection")
        if resp.get("error"):
            kind = resp.get("kind", "")
            if kind == "ValueError":
                raise ValueError(resp["error"])
            raise PluginError(resp["error"])
        return resp.get("result")


class ExternalDriver(PluginProcess, Driver):
    """Host-side proxy for one DRIVER plugin process: the in-process
    Driver interface implemented by socket RPC to the subprocess."""

    plugin_type = "driver"

    # ------------------------------------------------------ Driver surface

    def fingerprint(self) -> DriverInfo:
        try:
            out = self._call("Fingerprint")
            return DriverInfo(detected=bool(out.get("detected")),
                              healthy=bool(out.get("healthy")),
                              attributes=dict(out.get("attributes", {})))
        except Exception:               # noqa: BLE001 - dead plugin
            return DriverInfo(detected=False, healthy=False)

    def start_task(self, task_id, task, task_dir, env) -> TaskHandle:
        from ..api_codec import to_api
        out = self._call("StartTask", task_id=task_id, task=to_api(task),
                         task_dir=task_dir, env=dict(env))
        return TaskHandle(
            task_id=task_id, driver=self.name,
            pid=int(out.get("pid", 0)),
            started_at=float(out.get("started_at", time.time())))

    def wait_task(self, task_id, timeout=None) -> Optional[ExitResult]:
        out = self._call("WaitTask", task_id=task_id, timeout=timeout)
        if out is None:
            return None
        return ExitResult(exit_code=int(out.get("exit_code", 0)),
                          signal=int(out.get("signal", 0)),
                          err=out.get("err", ""))

    def stop_task(self, task_id, kill_timeout=5.0, sig="") -> None:
        self._call("StopTask", task_id=task_id, kill_timeout=kill_timeout,
                   sig=sig)

    def destroy_task(self, task_id) -> None:
        try:
            self._call("DestroyTask", task_id=task_id)
        except PluginError:
            pass

    def signal_task(self, task_id, sig) -> None:
        self._call("SignalTask", task_id=task_id, sig=sig)

    def task_stats(self, task_id) -> dict:
        return self._call("TaskStats", task_id=task_id) or {}

    def inspect_task(self, task_id) -> Optional[TaskHandle]:
        out = self._call("InspectTask", task_id=task_id)
        if out is None:
            return None
        return TaskHandle(task_id=task_id, driver=self.name,
                          pid=int(out.get("pid", 0)))

    def recover_task(self, handle: TaskHandle) -> bool:
        try:
            return bool(self._call("RecoverTask", task_id=handle.task_id,
                                   pid=handle.pid))
        except PluginError:
            return False

    def exec_task(self, task_id, command, tty: bool = False, cwd: str = "",
                  env=None):
        """Streaming exec proxied over the plugin socket (ref
        plugins/drivers/driver.go:577 ExecTaskStreamingRaw): ExecOpen
        mints a session in the plugin process; stdin/output/resize ride
        ExecIO/ExecResize round-trips."""
        out = self._call("ExecOpen", task_id=task_id,
                         command=list(command or []), tty=bool(tty),
                         cwd=cwd, env=dict(env or {}))
        return _RemoteExecSession(self, out["session"])


class _RemoteExecSession:
    """Host-side view of a plugin exec session, shaped like
    driver.ExecSession so the client HTTP exec endpoints can't tell a
    plugin task from a built-in one."""

    def __init__(self, drv: ExternalDriver, session_id: str):
        self._drv = drv
        self._sid = session_id
        self._out = bytearray()
        self._err = bytearray()
        self.exit_code: Optional[int] = None

    def _io(self, wait: float = 0.0, stdin: bytes = b"",
            close_stdin: bool = False) -> None:
        import base64
        r = self._drv._call(
            "ExecIO", session=self._sid, wait=wait,
            stdin=base64.b64encode(stdin).decode() if stdin else "",
            close_stdin=close_stdin) or {}
        self._out += base64.b64decode(r.get("stdout") or "")
        self._err += base64.b64decode(r.get("stderr") or "")
        if r.get("exited"):
            self.exit_code = r.get("exit_code")

    def write_stdin(self, data: bytes) -> None:
        self._io(stdin=data)

    def close_stdin(self) -> None:
        self._io(close_stdin=True)

    def resize(self, rows: int, cols: int) -> None:
        self._drv._call("ExecResize", session=self._sid, rows=rows,
                        cols=cols)

    def read_output(self, wait: float = 0.0) -> dict:
        # locally buffered chunks (from stdin round-trips) serve first;
        # otherwise poll the plugin, letting IT do the blocking wait
        if not self._out and not self._err and self.exit_code is None:
            self._io(wait=wait)
        out = {"stdout": bytes(self._out), "stderr": bytes(self._err),
               "exited": self.exit_code is not None,
               "exit_code": self.exit_code}
        self._out.clear()
        self._err.clear()
        return out

    def terminate(self) -> None:
        try:
            self._drv._call("ExecClose", session=self._sid)
        except PluginError:
            pass


class ExternalCSIPlugin(PluginProcess):
    """Host-side proxy for one CSI plugin process (ref
    plugins/csi/client.go, where CSI drivers are external gRPC
    processes — the entire point of CSI: third-party storage drivers
    ship independently of the orchestrator).

    Implements the CSIPluginClient contract (csimanager.py) over the
    plugin socket. A crashed plugin is RELAUNCHED on the next call: the
    claim state machine is pull-based and idempotent, so a detach that
    died mid-flight is simply retried against the fresh process."""

    plugin_type = "csi"

    @property
    def requires_controller(self) -> bool:
        return bool(self.info.get("requires_controller"))

    def _call_live(self, method: str, **params):
        """_call with crash recovery: relaunch a dead plugin process
        first (claims held by this node must survive plugin crashes —
        VERDICT r4 #2's recoverability requirement). A dedicated
        relaunch mutex (NOT self._lock — _launch itself RPCs through
        it) serializes concurrent recoverers, and the dead-check repeats
        inside it so the loser of the race adopts the winner's fresh
        process instead of spawning an orphaned second one."""
        with self._relaunch_lock:
            with self._lock:
                dead = self.proc is None or self.proc.poll() is not None \
                    or self._sock is None
            if dead:
                self.logger(f"csi: plugin {self.name!r} down; relaunching")
                try:
                    self.shutdown()
                # tearing down a process already observed dead; _launch
                # below raises loudly if the relaunch fails
                except Exception:  # nomadlint: disable=EXC001 — already dead
                    pass
                self._launch()
        return self._call(method, **params)

    # ------------------------------------------- CSIPluginClient surface

    def fingerprint(self) -> dict:
        try:
            return self._call_live("Fingerprint")
        except Exception:               # noqa: BLE001 — dead plugin
            return {"healthy": False, "provider": self.name,
                    "requires_controller": self.requires_controller}

    def node_stage_volume(self, volume_id: str, context: dict) -> None:
        self._call_live("NodeStageVolume", volume_id=volume_id,
                        context=dict(context or {}))

    def node_publish_volume(self, volume_id: str, target_path: str,
                            readonly: bool, context: dict) -> None:
        self._call_live("NodePublishVolume", volume_id=volume_id,
                        target_path=target_path, readonly=bool(readonly),
                        context=dict(context or {}))

    def node_unpublish_volume(self, volume_id: str,
                              target_path: str) -> None:
        self._call_live("NodeUnpublishVolume", volume_id=volume_id,
                        target_path=target_path)

    def controller_unpublish_volume(self, volume_id: str,
                                    node_id: str) -> None:
        self._call_live("ControllerUnpublishVolume", volume_id=volume_id,
                        node_id=node_id)


def discover_all(plugin_dir: str, logger=None) -> dict[str, dict]:
    """Launch every executable in plugin_dir and sort it by announced
    plugin type (ref client config plugin_dir + go-plugin Discover).
    Returns {"driver": {name: ExternalDriver},
             "csi": {name: ExternalCSIPlugin}}.
    Failures are logged and skipped — one bad plugin must not stop the
    client."""
    log = logger or (lambda msg: None)
    wrappers = {"driver": ExternalDriver, "csi": ExternalCSIPlugin}
    out: dict[str, dict] = {k: {} for k in wrappers}
    if not plugin_dir or not os.path.isdir(plugin_dir):
        return out
    for entry in sorted(os.listdir(plugin_dir)):
        path = os.path.join(plugin_dir, entry)
        if not os.path.isfile(path) or not os.access(path, os.X_OK):
            continue
        try:
            probe = PluginProcess([path], logger=log)
            ptype = probe.info.get("type", "")
            wrapper = wrappers.get(ptype)
            if wrapper is None:
                log(f"client: plugin {entry!r} announced unknown type "
                    f"{ptype!r}; skipping")
                probe.shutdown()
                continue
            plug = wrapper.adopt(probe)
            family = out[ptype]
            if plug.name in family:
                log(f"client: plugin {entry!r} duplicates {ptype} name "
                    f"{plug.name!r}; keeping the first")
                plug.shutdown()
                continue
            family[plug.name] = plug
            log(f"client: loaded external {ptype} plugin {plug.name!r} "
                f"(protocol v{plug.protocol_version})")
        except Exception as e:          # noqa: BLE001
            log(f"client: plugin {entry!r} failed to load: {e}")
    return out


def discover_plugins(plugin_dir: str, logger=None) -> dict[str, ExternalDriver]:
    """Driver-only view of discover_all (the original fabric surface)."""
    found = discover_all(plugin_dir, logger)
    for plug in found["csi"].values():      # not ours to keep here
        plug.shutdown()
    return found["driver"]
