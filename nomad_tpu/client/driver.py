"""Task driver interface + built-in drivers (ref plugins/drivers/driver.go:47
DriverPlugin and drivers/mock, drivers/rawexec).

The DriverPlugin contract: fingerprint / start_task / wait_task / stop_task /
destroy_task / inspect_task / recover_task. In-process here; the executor
subprocess boundary (ref drivers/shared/executor) arrives with the C++
runtime sidecar.
"""
from __future__ import annotations

import dataclasses
import os
import shlex
import signal
import subprocess
import threading
import time
from typing import Optional

from ..structs import DriverInfo


@dataclasses.dataclass
class TaskHandle:
    """Recoverable handle to a running task (ref drivers TaskHandle +
    reattach config)."""
    task_id: str = ""
    driver: str = ""
    pid: int = 0
    config: dict = dataclasses.field(default_factory=dict)
    started_at: float = 0.0


@dataclasses.dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    oom_killed: bool = False
    err: str = ""

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


class ExecSession:
    """One interactive exec-into-task stream (ref
    plugins/drivers/driver.go:69 ExecTaskStreaming,
    drivers/shared/executor ExecStreaming): a subprocess sharing the
    task's dir/env, optionally under a PTY, with non-blocking output
    drains feeding a bounded buffer."""

    def __init__(self, argv: list[str], cwd: str, env: dict[str, str],
                 tty: bool = False):
        import subprocess as sp
        self.tty = tty
        self._lock = threading.Lock()
        self._stdout = bytearray()
        self._stderr = bytearray()
        self._data = threading.Condition(self._lock)
        self.exit_code: Optional[int] = None
        full_env = dict(os.environ)
        full_env.update(env)
        self._drainers: list[threading.Thread] = []
        if tty:
            import pty
            self._master, slave = pty.openpty()
            self.proc = sp.Popen(argv, cwd=cwd, env=full_env,
                                 stdin=slave, stdout=slave, stderr=slave,
                                 start_new_session=True, close_fds=True)
            os.close(slave)
            t = threading.Thread(target=self._drain_pty, daemon=True)
            t.start()
            self._drainers.append(t)
        else:
            self._master = None
            self.proc = sp.Popen(argv, cwd=cwd, env=full_env,
                                 stdin=sp.PIPE, stdout=sp.PIPE,
                                 stderr=sp.PIPE, start_new_session=True)
            for pipe, buf in ((self.proc.stdout, self._stdout),
                              (self.proc.stderr, self._stderr)):
                t = threading.Thread(target=self._drain, daemon=True,
                                     args=(pipe, buf))
                t.start()
                self._drainers.append(t)
        threading.Thread(target=self._reap, daemon=True).start()

    def _drain(self, pipe, buf: bytearray) -> None:
        while True:
            chunk = pipe.read1(65536) if hasattr(pipe, "read1") else \
                pipe.read(65536)
            if not chunk:
                break
            with self._data:
                buf.extend(chunk)
                self._data.notify_all()

    def _drain_pty(self) -> None:
        while True:
            try:
                chunk = os.read(self._master, 65536)
            except OSError:
                break
            if not chunk:
                break
            with self._data:
                self._stdout.extend(chunk)
                self._data.notify_all()

    def _reap(self) -> None:
        code = self.proc.wait()
        # exit_code is only published AFTER the drain threads hit EOF, so
        # "exited with no pending output" really means all output was
        # delivered (a fixed sleep would race large final bursts)
        for t in self._drainers:
            t.join(timeout=5.0)
        with self._data:
            self.exit_code = code if code >= 0 else 128 - code
            self._data.notify_all()

    def write_stdin(self, data: bytes) -> None:
        if self.tty:
            os.write(self._master, data)
        elif self.proc.stdin:
            try:
                self.proc.stdin.write(data)
                self.proc.stdin.flush()
            except (BrokenPipeError, ValueError):
                pass

    def close_stdin(self) -> None:
        if self.tty:
            # a PTY has no half-close: deliver EOF as the line
            # discipline's VEOF character (^D)
            try:
                os.write(self._master, b"\x04")
            except OSError:
                pass
        elif self.proc.stdin:
            try:
                self.proc.stdin.close()
            except OSError:
                pass

    def resize(self, rows: int, cols: int) -> None:
        """ref drivers/driver.go TaskResizeCh"""
        if self._master is None:
            return
        import fcntl
        import struct
        import termios
        fcntl.ioctl(self._master, termios.TIOCSWINSZ,
                    struct.pack("HHHH", rows, cols, 0, 0))

    def read_output(self, wait: float = 0.0) -> dict:
        """Drain buffered output. Blocks up to `wait` seconds for new
        data or exit. -> {stdout, stderr, exited, exit_code}"""
        deadline = time.monotonic() + wait
        with self._data:
            while not self._stdout and not self._stderr and \
                    self.exit_code is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._data.wait(left)
            out = bytes(self._stdout)
            err = bytes(self._stderr)
            self._stdout.clear()
            self._stderr.clear()
            return {"stdout": out, "stderr": err,
                    "exited": self.exit_code is not None,
                    "exit_code": self.exit_code}

    def terminate(self) -> None:
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        if self._master is not None:
            try:
                os.close(self._master)
            except OSError:
                pass


def validate_config(config: dict, schema: dict) -> str:
    """Validate a task's driver config against the driver's declared
    schema (the hclspec analog, ref plugins/shared/hclspec + each
    driver's TaskConfig spec). Returns "" or an error string.

    schema: {key: {"type": "string"|"number"|"bool"|"list"|"map",
                   "required": bool, "default": any}}; unknown keys are
    rejected — the reference's hcl decoding errors the same way."""
    TYPES = {"string": str, "number": (int, float), "bool": bool,
             "list": (list, tuple), "map": dict,
             # args-style fields: a list OR a shell string the driver
             # shlex-splits
             "list_or_string": (list, tuple, str)}
    for key in config:
        if key not in schema:
            return (f"unknown driver config key {key!r} "
                    f"(known: {', '.join(sorted(schema)) or 'none'})")
    for key, spec in schema.items():
        if key not in config:
            if spec.get("required"):
                return f"missing required driver config key {key!r}"
            continue
        want = TYPES.get(spec.get("type", ""), object)
        val = config[key]
        # bools are ints in python; keep number/bool distinct like hcl
        if spec.get("type") == "number" and isinstance(val, bool):
            return f"driver config {key!r}: expected number, got bool"
        if not isinstance(val, want):
            return (f"driver config {key!r}: expected "
                    f"{spec.get('type')}, got {type(val).__name__}")
    return ""


class Driver:
    name = "driver"

    def fingerprint(self) -> DriverInfo:
        return DriverInfo(detected=True, healthy=True)

    def config_schema(self) -> Optional[dict]:
        """Declared task-config schema (hclspec analog); None skips
        validation (plugin drivers may validate internally)."""
        return None

    def bind_client(self, client) -> None:
        """Drivers needing cluster access (catalog resolution etc.) get
        the owning client after construction; default no-op."""

    def start_task(self, task_id: str, task, task_dir: str,
                   env: dict[str, str]) -> TaskHandle:
        raise NotImplementedError

    def wait_task(self, task_id: str, timeout: Optional[float] = None
                  ) -> Optional[ExitResult]:
        raise NotImplementedError

    def stop_task(self, task_id: str, kill_timeout: float = 5.0,
                  sig: str = "") -> None:
        raise NotImplementedError

    def destroy_task(self, task_id: str) -> None:
        pass

    def signal_task(self, task_id: str, sig: str) -> None:
        """Send a signal to a running task (ref DriverPlugin.SignalTask,
        plugins/drivers/driver.go:47)."""
        raise NotImplementedError(
            f"driver {self.name!r} does not support signaling")

    def task_stats(self, task_id: str) -> dict:
        """Point-in-time resource usage (ref DriverPlugin.TaskStats):
        {"cpu_percent": float, "memory_rss_bytes": int}."""
        return {"cpu_percent": 0.0, "memory_rss_bytes": 0}

    def exec_task(self, task_id: str, command: list[str], tty: bool = False,
                  cwd: str = "", env: Optional[dict] = None) -> ExecSession:
        """Interactive exec inside the task's context (ref
        plugins/drivers/driver.go:577 ExecTaskStreamingRaw). The base
        implementation spawns a host process in the task dir with the
        task env — correct for every host-process driver (raw_exec, mock,
        exec-without-namespaces); containerized drivers override to enter
        the task's isolation context."""
        if not command:
            raise ValueError("exec requires a command")
        return ExecSession(list(command), cwd=cwd or os.getcwd(),
                           env=env or {}, tty=tty)

    def inspect_task(self, task_id: str) -> Optional[TaskHandle]:
        return None

    def recover_task(self, handle: TaskHandle) -> bool:
        """Reattach after client restart; True if the task is still live."""
        return False


def read_proc_stats(pid: int) -> dict:
    """Read one process's usage from /proc (ref client/stats and the
    executor's TaskStats: total_ticks + RSS)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            rss_pages = int(f.read().split()[1])
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(") ", 1)[1].split()
        utime, stime = int(fields[11]), int(fields[12])
        hz = os.sysconf("SC_CLK_TCK")
        return {
            "cpu_percent": 0.0,   # needs two samples; ticks are the basis
            "cpu_total_ticks": (utime + stime) / hz,
            "memory_rss_bytes": rss_pages * os.sysconf("SC_PAGE_SIZE"),
        }
    except (OSError, IndexError, ValueError):
        return {"cpu_percent": 0.0, "memory_rss_bytes": 0}


def _seconds(v) -> float:
    """Accept bare seconds or duration strings ('10s', '1m') — the reference
    mock driver's config takes Go duration strings (drivers/mock)."""
    if isinstance(v, (int, float)):
        return float(v)
    from ..jobspec import duration
    return duration(str(v))


class MockDriver(Driver):
    """Configurable fake driver for tests (ref drivers/mock): config keys
    run_for (sec or duration string), exit_code, start_error, kill_after."""

    name = "mock_driver"

    def config_schema(self):
        # run_for/kill_after accept seconds OR duration strings -> no
        # type constraint (hclspec would model this as a union)
        return {"run_for": {}, "kill_after": {},
                "exit_code": {"type": "number"},
                "start_error": {"type": "string"},
                "signal_error": {"type": "string"}}

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: dict[str, dict] = {}

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        if cfg.get("start_error"):
            raise RuntimeError(cfg["start_error"])
        now = time.time()
        rec = {
            "ends_at": now + _seconds(cfg.get("run_for", 0.0)),
            "exit_code": int(cfg.get("exit_code", 0)),
            "stopped": threading.Event(),
            "started_at": now,
            "signals": [],
        }
        with self._lock:
            self._tasks[task_id] = rec
        return TaskHandle(task_id=task_id, driver=self.name, started_at=now)

    def wait_task(self, task_id, timeout=None):
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return ExitResult(err="unknown task")
        deadline = time.time() + timeout if timeout is not None else None
        while True:
            remaining = rec["ends_at"] - time.time()
            if rec["stopped"].is_set():
                return ExitResult(exit_code=0, signal=9)
            if remaining <= 0:
                return ExitResult(exit_code=rec["exit_code"])
            if deadline is not None and time.time() >= deadline:
                return None
            rec["stopped"].wait(min(0.05, max(0.01, remaining)))

    def stop_task(self, task_id, kill_timeout=5.0, sig=""):
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec:
            rec["stopped"].set()

    def destroy_task(self, task_id):
        with self._lock:
            self._tasks.pop(task_id, None)

    def signal_task(self, task_id, sig):
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            raise ValueError("unknown task")
        rec["signals"].append(sig)

    def received_signals(self, task_id) -> list[str]:
        with self._lock:
            rec = self._tasks.get(task_id)
        return list(rec["signals"]) if rec else []

    def recover_task(self, handle):
        with self._lock:
            return handle.task_id in self._tasks


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
# native out-of-process log collector (ref client/logmon: a subprocess
# per task stream so the agent never holds task IO); built by
# `make -C native`, absent -> drivers append directly and the Python
# LogRotator handles rotation
LOGMON_BIN = os.path.join(_REPO_ROOT, "native", "nomad-logmon")


def logmon_available() -> bool:
    return os.access(LOGMON_BIN, os.X_OK)


def _open_log_sinks(task_dir: str, task):
    """(stdout_sink, stderr_sink, logmon_procs): pipes into per-stream
    nomad-logmon subprocesses when the native binary is built, plain
    O_APPEND files otherwise. Callers close the returned sinks after
    handing them to the task process."""
    lc = getattr(task, "log_config", None)
    max_bytes = (getattr(lc, "max_file_size_mb", 10) or 10) * 1024 * 1024
    max_files = getattr(lc, "max_files", 10) or 10
    if logmon_available():
        procs = []
        sinks = []
        try:
            for stream in ("stdout", "stderr"):
                base = os.path.join(task_dir, f"{task.name}.{stream}.log")
                p = subprocess.Popen(
                    [LOGMON_BIN, base, str(max_bytes), str(max_files)],
                    stdin=subprocess.PIPE, start_new_session=True)
                procs.append(p)
                sinks.append(p.stdin)
        except BaseException:
            # second spawn failed: close the first sidecar's pipe so it
            # sees EOF and exits rather than leaking on read()
            for f in sinks:
                try:
                    f.close()
                except OSError:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    p.kill()
            raise
        return sinks[0], sinks[1], procs
    # task stdout/stderr streams: loss-tolerant by contract (the
    # reference loses in-flight log bytes on power loss too); not
    # control-plane state
    # nomadlint: disable=DUR001 — loss-tolerant log stream
    stdout = open(os.path.join(task_dir, f"{task.name}.stdout.log"), "ab")
    # nomadlint: disable=DUR001 — task log stream, see above
    stderr = open(os.path.join(task_dir, f"{task.name}.stderr.log"), "ab")
    return stdout, stderr, []


class ConnectProxyDriver(Driver):
    """The sidecar data plane for connect_admission-injected proxy tasks
    (ref envoy in the reference; here an in-process threaded TCP proxy —
    see integrations/connect.py for the mesh wiring). Ingress listener:
    allocated dynamic port -> 127.0.0.1:<local service port>. Upstream
    listeners: 127.0.0.1:<local_bind_port> -> a healthy catalog instance
    of the destination, resolved PER CONNECTION through the client's RPC
    (instances move; the mesh follows)."""

    name = "connect_proxy"

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: dict[str, dict] = {}
        self._client = None

    def bind_client(self, client) -> None:
        self._client = client

    _AUTHZ_TTL = 2.0

    def _resolver(self, namespace: str, source: str, destination: str):
        authz_cache = [0.0, True]       # (expiry, allowed)

        def resolve():
            client = self._client
            if client is None:
                return None
            try:
                # mesh authorization: the proxy enforces intentions (the
                # envoy-RBAC analog; ref Consul intentions — which pushes
                # cached intentions to proxies). A short-TTL cache keeps
                # the data plane at ~one authz RPC per TTL instead of one
                # per connection; default allow with no matching rule.
                now = time.monotonic()
                if now >= authz_cache[0]:
                    authz_cache[1] = client.rpc.intention_allowed(
                        namespace, source, destination)
                    authz_cache[0] = now + self._AUTHZ_TTL
                if not authz_cache[1]:
                    client.logger(
                        f"connect-proxy: intention denies "
                        f"{source} -> {destination}")
                    return None
                instances = client.rpc.service_instances(namespace,
                                                         destination)
            except Exception:           # noqa: BLE001 — servers away
                return None
            healthy = [i for i in instances
                       if getattr(i, "status", "passing") == "passing"]
            if not healthy:
                return None
            inst = healthy[int(time.time() * 1000) % len(healthy)]
            return (inst.address, inst.port)
        return resolve

    def start_task(self, task_id, task, task_dir, env):
        from ..integrations.connect import _Forwarder
        cfg = task.config
        logger = (self._client.logger if self._client is not None
                  else (lambda m: None))
        from .taskenv import _env_key
        forwarders: list = []
        ingress_label = _env_key(cfg.get("ingress_port_label", ""))
        ingress_port = int(env.get(f"NOMAD_PORT_{ingress_label}", 0) or 0)
        svc_label = _env_key(cfg.get("local_service_port_label", ""))
        svc_port = int(env.get(f"NOMAD_PORT_{svc_label}", 0) or 0)
        if ingress_port and svc_port:
            forwarders.append(_Forwarder(
                ("0.0.0.0", ingress_port),
                lambda: ("127.0.0.1", svc_port), logger,
                name=f"connect-ingress-{task_id[:8]}"))
        ns = cfg.get("namespace", "default")
        for up in cfg.get("upstreams", []):
            forwarders.append(_Forwarder(
                ("127.0.0.1", int(up["local_bind_port"])),
                self._resolver(ns, cfg.get("service", ""),
                               up["destination"]), logger,
                name=f"connect-up-{up['destination']}-{task_id[:8]}"))
        # expose-path listeners (ref job_endpoint_hook_expose_check.go +
        # envoy expose paths): health-check paths served on their own
        # ports through the sidecar, everything else 403'd
        from ..integrations.connect import ExposeForwarder
        for ex in cfg.get("expose", []) or []:
            ex_label = _env_key(ex.get("listener_port_label", ""))
            ex_port = int(env.get(f"NOMAD_PORT_{ex_label}", 0) or 0)
            # the reference allows a check's local path port to differ
            # from the service port (expose.path local_path_port); honor
            # the entry's own label and fall back to the service port
            lp_label = _env_key(ex.get("local_path_port_label", ""))
            lp_port = int(env.get(f"NOMAD_PORT_{lp_label}", 0) or 0) \
                or svc_port
            if ex_port and lp_port:
                forwarders.append(ExposeForwarder(
                    ("0.0.0.0", ex_port),
                    lambda lp=lp_port: ("127.0.0.1", lp), logger,
                    name=f"connect-expose-{task_id[:8]}",
                    path=ex.get("path", "/")))
        for f in forwarders:
            f.start()
        rec = {"forwarders": forwarders, "stopped": threading.Event(),
               "started_at": time.time()}
        with self._lock:
            self._tasks[task_id] = rec
        return TaskHandle(task_id=task_id, driver=self.name,
                          started_at=rec["started_at"])

    def wait_task(self, task_id, timeout=None):
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return ExitResult(err="unknown task")
        if rec["stopped"].wait(timeout):
            return ExitResult(exit_code=0)
        return None

    def stop_task(self, task_id, kill_timeout=5.0, sig=""):
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            return
        for f in rec["forwarders"]:
            f.stop()
        rec["stopped"].set()

    def destroy_task(self, task_id):
        self.stop_task(task_id)
        with self._lock:
            self._tasks.pop(task_id, None)

    def inspect_task(self, task_id):
        with self._lock:
            rec = self._tasks.get(task_id)
        if rec is None:
            raise KeyError(task_id)
        return {"connections": sum(f.connections
                                   for f in rec["forwarders"])}


class RawExecDriver(Driver):
    """Fork/exec without isolation (ref drivers/rawexec): config keys
    command, args."""

    name = "raw_exec"

    def config_schema(self):
        return {"command": {"type": "string", "required": True},
                "args": {"type": "list_or_string"}}

    def __init__(self):
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}
        self._logmons: dict[str, list] = {}

    def uses_logmon(self) -> bool:
        """True when this driver routes task output through the native
        nomad-logmon sidecar (which then owns rotation)."""
        return logmon_available()

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        command = cfg.get("command", "")
        if not command:
            raise ValueError("raw_exec requires config.command")
        args = cfg.get("args", [])
        if isinstance(args, str):
            args = shlex.split(args)
        full_env = dict(os.environ)
        full_env.update(env)
        stdout, stderr, logmons = _open_log_sinks(task_dir, task)

        def _close_sinks():
            # the parent's copies of the pipe write-ends must close so
            # each logmon sees EOF when the TASK exits
            for f in (stdout, stderr):
                try:
                    f.close()
                except OSError:
                    pass

        try:
            proc = subprocess.Popen(
                [command] + list(args), cwd=task_dir, env=full_env,
                stdout=stdout, stderr=stderr,
                start_new_session=True)  # own process group for clean kill
        except BaseException:
            # Popen raised (bad command): close the write-ends so the
            # sidecars see EOF and exit instead of leaking on read()
            _close_sinks()
            for p in logmons:
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    p.kill()
            raise
        _close_sinks()
        with self._lock:
            self._procs[task_id] = proc
            if logmons:
                self._logmons[task_id] = logmons
        return TaskHandle(task_id=task_id, driver=self.name, pid=proc.pid,
                          started_at=time.time())

    def _drain_logmons(self, task_id) -> None:
        """After task exit, wait briefly for the logmon sidecars to see
        EOF and flush, so callers reading the log files observe all
        output (the reference's logmon shutdown barrier)."""
        with self._lock:
            logmons = self._logmons.pop(task_id, [])
        for p in logmons:
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                p.kill()

    def wait_task(self, task_id, timeout=None):
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None:
            return ExitResult(err="unknown task")
        try:
            code = proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None
        if code is None:
            return None
        self._drain_logmons(task_id)
        if code < 0:
            return ExitResult(exit_code=0, signal=-code)
        return ExitResult(exit_code=code)

    def stop_task(self, task_id, kill_timeout=5.0, sig=""):
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None or proc.poll() is not None:
            return
        signum = getattr(signal, sig, signal.SIGINT) if sig else signal.SIGINT
        try:
            os.killpg(os.getpgid(proc.pid), signum)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + kill_timeout
        while time.time() < deadline:
            if proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def destroy_task(self, task_id):
        self.stop_task(task_id, kill_timeout=0.1)
        self._drain_logmons(task_id)
        with self._lock:
            self._procs.pop(task_id, None)

    def signal_task(self, task_id, sig):
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None or proc.poll() is not None:
            raise ValueError("task not running")
        signum = getattr(signal, sig, None)
        if signum is None:
            raise ValueError(f"invalid signal {sig!r}")
        os.killpg(os.getpgid(proc.pid), signum)

    def task_stats(self, task_id):
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None or proc.poll() is not None:
            return super().task_stats(task_id)
        return read_proc_stats(proc.pid)

    def recover_task(self, handle):
        if handle.pid <= 0:
            return False
        try:
            os.kill(handle.pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            pass
        # re-wrap the pid so wait/stop work post-restart
        proc = _ReattachedProcess(handle.pid)
        with self._lock:
            self._procs[handle.task_id] = proc   # type: ignore[assignment]
        return True


class _ReattachedProcess:
    """Minimal Popen-alike over a bare pid for post-restart reattach."""

    def __init__(self, pid: int):
        self.pid = pid
        self._code: Optional[int] = None

    def poll(self):
        if self._code is not None:
            return self._code
        try:
            os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            self._code = 0
            return self._code

    def wait(self, timeout=None):
        deadline = time.time() + timeout if timeout is not None else None
        while True:
            code = self.poll()
            if code is not None:
                return code
            if deadline is not None and time.time() >= deadline:
                raise subprocess.TimeoutExpired(cmd=f"pid:{self.pid}",
                                                timeout=timeout)
            time.sleep(0.05)


def _exec_driver():
    from .exec_driver import ExecDriver
    return ExecDriver()


def _java_driver():
    from .ext_drivers import JavaDriver
    return JavaDriver()


def _qemu_driver():
    from .ext_drivers import QemuDriver
    return QemuDriver()


def _docker_driver():
    from .ext_drivers import DockerDriver
    return DockerDriver()


BUILTIN_DRIVERS = {
    "mock_driver": MockDriver,
    "raw_exec": RawExecDriver,
    "connect_proxy": ConnectProxyDriver,   # the sidecar data plane
    "exec": _exec_driver,       # native C++ executor supervisor
    "java": _java_driver,
    "qemu": _qemu_driver,       # gated: fingerprints only with qemu present
    "docker": _docker_driver,   # gated: fingerprints only with a live daemon
}
