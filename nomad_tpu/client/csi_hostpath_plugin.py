"""Hostpath CSI plugin as an EXTERNAL PROCESS (the upstream
csi-driver-host-path analog; ref plugins/csi/client.go — third-party CSI
drivers are separate processes behind the plugin boundary).

Drop an executable shim into the client's plugin_dir:

    #!/usr/bin/env python3
    from nomad_tpu.client.csi_hostpath_plugin import main
    main()

The volume base directory comes from $NOMAD_CSI_HOSTPATH_DIR (default
/opt/nomad-csi-hostpath). The same HostPathCSIPlugin class also runs
in-process for unit tests; this module is only the process boundary."""
from __future__ import annotations

import os


def main() -> None:
    from .csimanager import HostPathCSIPlugin
    from .plugin_runtime import serve_csi
    base = os.environ.get("NOMAD_CSI_HOSTPATH_DIR",
                          "/opt/nomad-csi-hostpath")
    serve_csi(HostPathCSIPlugin(base))


if __name__ == "__main__":
    main()
