"""Client CSI manager (ref client/pluginmanager/csimanager/: volume
staging/publishing for allocs + plugin fingerprinting into the node).

The reference talks gRPC to external CSI plugin processes (plugins/csi/).
Here the plugin boundary is the `CSIPluginClient` interface; the built-in
`HostPathCSIPlugin` implements it with node-local directories (the upstream
csi-driver-host-path analog), which is also what tests exercise. Real
drivers slot in behind the same stage/publish/unpublish contract.
"""
from __future__ import annotations

import os
import shutil
from typing import Optional

from ..structs.csi import CSIVolumeClaim, CLAIM_READ, CLAIM_STATE_READY_TO_FREE, CLAIM_WRITE


class CSIPluginClient:
    """ref plugins/csi CSIPlugin interface (node service subset)."""

    name = "csi-plugin"
    requires_controller = False

    def fingerprint(self) -> dict:
        return {"healthy": True, "provider": self.name,
                "provider_version": "0.1.0",
                "requires_controller": self.requires_controller}

    def node_stage_volume(self, volume_id: str, context: dict) -> None:
        pass

    def node_publish_volume(self, volume_id: str, target_path: str,
                            readonly: bool, context: dict) -> None:
        raise NotImplementedError

    def node_unpublish_volume(self, volume_id: str,
                              target_path: str) -> None:
        raise NotImplementedError


class HostPathCSIPlugin(CSIPluginClient):
    """Node-local directory-backed volumes (the csi-driver-host-path
    pattern): publish = symlink the per-volume dir at the target path."""

    name = "hostpath"

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _vol_dir(self, volume_id: str) -> str:
        return os.path.join(self.base_dir, volume_id)

    def node_stage_volume(self, volume_id: str, context: dict) -> None:
        os.makedirs(self._vol_dir(volume_id), exist_ok=True)

    def node_publish_volume(self, volume_id, target_path, readonly, context):
        os.makedirs(os.path.dirname(target_path), exist_ok=True)
        if os.path.islink(target_path):
            os.unlink(target_path)
        os.symlink(self._vol_dir(volume_id), target_path)

    def node_unpublish_volume(self, volume_id, target_path):
        if os.path.islink(target_path):
            os.unlink(target_path)
        elif os.path.isdir(target_path):
            shutil.rmtree(target_path, ignore_errors=True)


class CSIManager:
    """Per-client manager: claims volumes through the servers and drives the
    node plugin's stage/publish lifecycle for each alloc (ref
    csimanager/volume.go MountVolume/UnmountVolume)."""

    def __init__(self, client):
        self.client = client
        self.plugins: dict[str, CSIPluginClient] = {}
        # (alloc_id, vol_id) -> (plugin_id, target_path)
        self._mounts: dict[tuple[str, str], tuple[str, str]] = {}

    def register_plugin(self, plugin_id: str,
                        plugin: CSIPluginClient) -> None:
        self.plugins[plugin_id] = plugin

    def fingerprint(self) -> dict[str, dict]:
        """node.csi_node_plugins payload."""
        return {pid: p.fingerprint() for pid, p in self.plugins.items()}

    # ------------------------------------------------------------- mounts

    def mount_volume(self, alloc, req) -> str:
        """Claim + stage + publish; returns the alloc-local mount path
        (ref csimanager MountVolume)."""
        ns = alloc.namespace
        vol = self.client.rpc.csi_volume_get(ns, req.source)
        if vol is None:
            raise ValueError(f"CSI volume {req.source!r} not found")
        plugin = self.plugins.get(vol.plugin_id)
        if plugin is None:
            raise ValueError(
                f"node has no CSI plugin {vol.plugin_id!r}")
        mode = CLAIM_READ if req.read_only else CLAIM_WRITE
        claim = CSIVolumeClaim(alloc_id=alloc.id,
                               node_id=self.client.node.id, mode=mode)
        self.client.rpc.csi_volume_claim(ns, vol.id, claim)
        # record before publish: a failed stage/publish must still release
        # the claim in Postrun (unmount_all)
        target = os.path.join(self.client.alloc_dir_root, alloc.id,
                              "volumes", req.name)
        self._mounts[(alloc.id, vol.id)] = (vol.plugin_id, target)
        plugin.node_stage_volume(vol.id, vol.context)
        plugin.node_publish_volume(vol.id, target, req.read_only,
                                   vol.context)
        return target

    def unmount_all(self, alloc) -> None:
        """Unpublish + release every claim this alloc holds (ref
        csimanager UnmountVolume + csi_hook Postrun)."""
        for (alloc_id, vol_id), (plugin_id, target) in \
                list(self._mounts.items()):
            if alloc_id != alloc.id:
                continue
            plugin = self.plugins.get(plugin_id)
            if plugin is not None:
                try:
                    plugin.node_unpublish_volume(vol_id, target)
                except Exception as e:  # noqa: BLE001 — must keep releasing
                    self.client.logger(f"csi: unpublish failed: {e!r}")
            try:
                self.client.rpc.csi_volume_claim(
                    alloc.namespace, vol_id,
                    CSIVolumeClaim(alloc_id=alloc.id,
                                   node_id=self.client.node.id,
                                   state=CLAIM_STATE_READY_TO_FREE))
            except Exception as e:      # noqa: BLE001 — server may be gone
                self.client.logger(f"csi: release claim failed: {e!r}")
            del self._mounts[(alloc_id, vol_id)]
