"""Client CSI manager (ref client/pluginmanager/csimanager/: volume
staging/publishing for allocs + plugin fingerprinting into the node).

The reference talks gRPC to external CSI plugin processes (plugins/csi/).
Here the plugin boundary is the `CSIPluginClient` interface; the built-in
`HostPathCSIPlugin` implements it with node-local directories (the upstream
csi-driver-host-path analog), which is also what tests exercise. Real
drivers slot in behind the same stage/publish/unpublish contract.
"""
from __future__ import annotations

import os
import shutil
from typing import Optional

from ..structs.csi import CSIVolumeClaim, CLAIM_READ, CLAIM_STATE_READY_TO_FREE, CLAIM_WRITE


class CSIPluginClient:
    """ref plugins/csi CSIPlugin interface (node service subset)."""

    name = "csi-plugin"
    requires_controller = False

    def fingerprint(self) -> dict:
        return {"healthy": True, "provider": self.name,
                "provider_version": "0.1.0",
                "requires_controller": self.requires_controller}

    def node_stage_volume(self, volume_id: str, context: dict) -> None:
        pass

    def node_publish_volume(self, volume_id: str, target_path: str,
                            readonly: bool, context: dict) -> None:
        raise NotImplementedError

    def node_unpublish_volume(self, volume_id: str,
                              target_path: str) -> None:
        raise NotImplementedError

    def controller_unpublish_volume(self, volume_id: str,
                                    node_id: str) -> None:
        """Detach the volume from the node at the storage backend (ref
        plugins/csi ControllerUnpublishVolume). Only meaningful for
        plugins with requires_controller; default no-op."""


class HostPathCSIPlugin(CSIPluginClient):
    """Node-local directory-backed volumes (the csi-driver-host-path
    pattern): publish = symlink the per-volume dir at the target path."""

    name = "hostpath"

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _vol_dir(self, volume_id: str) -> str:
        return os.path.join(self.base_dir, volume_id)

    def node_stage_volume(self, volume_id: str, context: dict) -> None:
        os.makedirs(self._vol_dir(volume_id), exist_ok=True)

    def node_publish_volume(self, volume_id, target_path, readonly, context):
        os.makedirs(os.path.dirname(target_path), exist_ok=True)
        if os.path.islink(target_path):
            os.unlink(target_path)
        os.symlink(self._vol_dir(volume_id), target_path)

    def node_unpublish_volume(self, volume_id, target_path):
        if os.path.islink(target_path):
            os.unlink(target_path)
        elif os.path.isdir(target_path):
            shutil.rmtree(target_path, ignore_errors=True)


class CSIManager:
    """Per-client manager: claims volumes through the servers and drives the
    node plugin's stage/publish lifecycle for each alloc (ref
    csimanager/volume.go MountVolume/UnmountVolume)."""

    def __init__(self, client):
        self.client = client
        self.plugins: dict[str, CSIPluginClient] = {}
        self.controller_plugins: dict[str, CSIPluginClient] = {}
        # (alloc_id, vol_id) -> (plugin_id, target_path)
        self._mounts: dict[tuple[str, str], tuple[str, str]] = {}

    def register_plugin(self, plugin_id: str, plugin: CSIPluginClient,
                        controller: bool = False) -> None:
        self.plugins[plugin_id] = plugin
        if controller or plugin.requires_controller:
            self.controller_plugins[plugin_id] = plugin

    def fingerprint(self) -> dict[str, dict]:
        """node.csi_node_plugins payload."""
        return {pid: p.fingerprint() for pid, p in self.plugins.items()}

    def fingerprint_controllers(self) -> dict[str, dict]:
        """node.csi_controller_plugins payload."""
        return {pid: p.fingerprint()
                for pid, p in self.controller_plugins.items()}

    # ------------------------------------------------------------- mounts

    def mount_volume(self, alloc, req) -> str:
        """Claim + stage + publish; returns the alloc-local mount path
        (ref csimanager MountVolume)."""
        ns = alloc.namespace
        vol = self.client.rpc.csi_volume_get(ns, req.source)
        if vol is None:
            raise ValueError(f"CSI volume {req.source!r} not found")
        plugin = self.plugins.get(vol.plugin_id)
        if plugin is None:
            raise ValueError(
                f"node has no CSI plugin {vol.plugin_id!r}")
        mode = CLAIM_READ if req.read_only else CLAIM_WRITE
        claim = CSIVolumeClaim(alloc_id=alloc.id,
                               node_id=self.client.node.id, mode=mode)
        self.client.rpc.csi_volume_claim(ns, vol.id, claim)
        # record before publish: a failed stage/publish must still release
        # the claim in Postrun (unmount_all)
        target = os.path.join(self.client.alloc_dir_root, alloc.id,
                              "volumes", req.name)
        self._mounts[(alloc.id, vol.id)] = (vol.plugin_id, target)
        plugin.node_stage_volume(vol.id, vol.context)
        plugin.node_publish_volume(vol.id, target, req.read_only,
                                   vol.context)
        return target

    # ---------------------------------------------- watcher-driven detach

    def reconcile_claims(self) -> int:
        """The client half of the volume watcher's unpublish state machine
        (ref volumewatcher/volume_watcher.go + csi_hook): the server marks
        which claims need node/controller detach; this node performs the
        plugin RPCs it can serve and confirms via claim updates. Pull
        model — the client polls, matching the alloc-watch design — so no
        server->client channel is needed. Returns detaches performed."""
        from ..structs.csi import (
            CLAIM_STATE_CONTROLLER_DETACHED, CLAIM_STATE_NODE_DETACHED,
        )
        done = 0
        node_id = self.client.node.id
        try:
            pending = self.client.rpc.csi_node_detach_pending(node_id)
        except Exception:           # noqa: BLE001 — servers unreachable
            return done
        for item in pending:
            plugin = self.plugins.get(item["plugin_id"])
            if plugin is None:
                continue
            target = self._detach_target(item["alloc_id"], item["volume_id"])
            try:
                plugin.node_unpublish_volume(item["volume_id"], target)
                self.client.rpc.csi_volume_claim(
                    item["namespace"], item["volume_id"],
                    CSIVolumeClaim(alloc_id=item["alloc_id"],
                                   node_id=node_id,
                                   state=CLAIM_STATE_NODE_DETACHED))
                done += 1
            except Exception as e:  # noqa: BLE001 — retried next pass
                self.client.logger(f"csi: node detach failed: {e!r}")
        try:
            pending = self.client.rpc.csi_controller_detach_pending(
                list(self.controller_plugins), node_id)
        except Exception:           # noqa: BLE001
            return done
        for item in pending:
            plugin = self.controller_plugins.get(item["plugin_id"])
            if plugin is None:
                continue
            try:
                plugin.controller_unpublish_volume(item["volume_id"],
                                                   item["node_id"])
                self.client.rpc.csi_volume_claim(
                    item["namespace"], item["volume_id"],
                    CSIVolumeClaim(alloc_id=item["alloc_id"],
                                   node_id=item["node_id"],
                                   state=CLAIM_STATE_CONTROLLER_DETACHED))
                done += 1
            except Exception as e:  # noqa: BLE001 — retried next pass
                self.client.logger(f"csi: controller detach failed: {e!r}")
        return done

    def _detach_target(self, alloc_id: str, vol_id: str) -> str:
        """Mount target for a claim — from the live mount record, or the
        conventional path when this client restarted and lost the map."""
        rec = self._mounts.get((alloc_id, vol_id))
        if rec is not None:
            return rec[1]
        vol_dir = os.path.join(self.client.alloc_dir_root, alloc_id,
                               "volumes")
        if os.path.isdir(vol_dir):
            for name in os.listdir(vol_dir):
                path = os.path.join(vol_dir, name)
                if os.path.islink(path) and \
                        os.path.basename(os.readlink(path)) == vol_id:
                    return path
        return os.path.join(vol_dir, vol_id)

    def unmount_all(self, alloc) -> None:
        """Unpublish + release every claim this alloc holds (ref
        csimanager UnmountVolume + csi_hook Postrun)."""
        for (alloc_id, vol_id), (plugin_id, target) in \
                list(self._mounts.items()):
            if alloc_id != alloc.id:
                continue
            plugin = self.plugins.get(plugin_id)
            if plugin is not None:
                try:
                    plugin.node_unpublish_volume(vol_id, target)
                except Exception as e:  # noqa: BLE001 — must keep releasing
                    self.client.logger(f"csi: unpublish failed: {e!r}")
            # a requires_controller plugin still owes the CONTROLLER
            # unpublish round: release only to node-detached and let the
            # volume watcher drive the controller RPC (free would leave
            # the volume attached at the storage backend). Controller-
            # less plugins free directly — the common fast path.
            from ..structs.csi import CLAIM_STATE_NODE_DETACHED
            state = CLAIM_STATE_READY_TO_FREE
            if plugin is not None and plugin.requires_controller:
                state = CLAIM_STATE_NODE_DETACHED
            try:
                self.client.rpc.csi_volume_claim(
                    alloc.namespace, vol_id,
                    CSIVolumeClaim(alloc_id=alloc.id,
                                   node_id=self.client.node.id,
                                   state=state))
            except Exception as e:      # noqa: BLE001 — server may be gone
                self.client.logger(f"csi: release claim failed: {e!r}")
            del self._mounts[(alloc_id, vol_id)]
