"""Host fingerprinting (ref client/fingerprint/ — arch, cpu, memory,
storage, network, host, nomad version — one fingerprinter per concern,
merged into the Node)."""
from __future__ import annotations

import os
import platform
import shutil
import socket
import uuid

from ..structs import (
    NetworkResource, Node, NodeCpuResources, NodeDiskResources,
    NodeMemoryResources, NodeNetworkResource, NodeResources,
)
from .. import __version__


def _cpu_mhz_total() -> tuple[int, int]:
    """(total MHz across cores, core count)"""
    cores = os.cpu_count() or 1
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except OSError:
        pass
    return int(mhz * cores), cores


def _memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 1024


def _host_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


# --------------------------------------------------------- fingerprinters
#
# One function per concern, the reference's registry shape
# (client/fingerprint/fingerprint.go hostFingerprinters): each takes the
# node + a config dict and merges attributes/resources/links in. All are
# best-effort — a fingerprinter that can't read its source contributes
# nothing, it never fails node startup.

def fp_arch(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/arch.go"""
    node.attributes["arch"] = platform.machine()


def fp_cpu(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/cpu.go"""
    cpu_mhz, cores = _cpu_mhz_total()
    node.attributes["cpu.numcores"] = str(cores)
    node.attributes["cpu.totalcompute"] = str(cpu_mhz)
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    node.attributes["cpu.modelname"] = \
                        line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    node.node_resources.cpu = NodeCpuResources(
        cpu_shares=cpu_mhz, total_core_count=cores,
        reservable_cores=list(range(cores)))


def fp_memory(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/memory.go"""
    mb = _memory_mb()
    node.attributes["memory.totalbytes"] = str(mb * 1024 * 1024)
    node.node_resources.memory = NodeMemoryResources(memory_mb=mb)


def fp_storage(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/storage.go: free/total bytes of the alloc
    dir's volume."""
    path = cfg.get("data_dir", "/tmp")
    try:
        usage = shutil.disk_usage(path)
        free_mb = usage.free // (1024 * 1024)
        node.attributes["unique.storage.volume"] = path
        node.attributes["unique.storage.bytestotal"] = str(usage.total)
        node.attributes["unique.storage.bytesfree"] = str(usage.free)
    except OSError:
        free_mb = 10 * 1024     # keep the node schedulable (stale mount)
    node.node_resources.disk = NodeDiskResources(disk_mb=free_mb)


def fp_host(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/host.go"""
    node.attributes["kernel.name"] = platform.system().lower()
    node.attributes["kernel.version"] = platform.release()
    node.attributes["os.name"] = platform.system().lower()
    node.attributes["os.version"] = platform.version()
    node.attributes["unique.hostname"] = platform.node()


def fp_nomad(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/nomad.go"""
    node.attributes["nomad.version"] = __version__
    node.attributes["nomad.revision"] = "tpu"


def fp_signal(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/signal.go: signals drivers can deliver."""
    import signal as _signal
    names = sorted(s.name for s in _signal.Signals
                   if s.name.startswith("SIG") and
                   not s.name.startswith("SIGRT"))
    node.attributes["os.signals"] = ",".join(names)


def fp_cgroup(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/cgroup_linux.go: cgroup mount + version."""
    if os.path.isdir("/sys/fs/cgroup"):
        v2 = os.path.exists("/sys/fs/cgroup/cgroup.controllers")
        node.attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
        node.attributes["unique.cgroup.version"] = "v2" if v2 else "v1"


def fp_bridge(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/bridge_linux.go: bridge kernel module."""
    for probe in ("/sys/module/bridge",
                  "/proc/sys/net/bridge"):
        if os.path.exists(probe):
            node.attributes["plugins.cni.version.bridge"] = "host"
            node.attributes["nomad.bridge.available"] = "true"
            return


def fp_network(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/network.go: default-route interface + all
    link-up interfaces from /sys/class/net with speeds."""
    ip = _host_ip()
    node.attributes["unique.network.ip-address"] = ip
    dev, speed = "eth0", 1000
    try:
        ifaces = sorted(os.listdir("/sys/class/net"))
    except OSError:
        ifaces = []
    up = []
    for i in ifaces:
        if i == "lo":
            continue
        try:
            with open(f"/sys/class/net/{i}/operstate") as f:
                state = f.read().strip()
        except OSError:
            state = "unknown"
        if state not in ("up", "unknown"):
            continue
        mbits = 1000
        try:
            with open(f"/sys/class/net/{i}/speed") as f:
                mbits = max(int(f.read().strip()), 0) or 1000
        except (OSError, ValueError):
            pass
        up.append((i, mbits))
    if up:
        dev, speed = up[0]
    node.attributes["unique.network.interface"] = dev
    node.node_resources.networks = [NetworkResource(
        device=dev, ip=ip, cidr=f"{ip}/32", mbits=speed)]
    node.node_resources.node_networks = [NodeNetworkResource(
        mode="host", device=dev, speed=speed,
        addresses=[{"alias": "default", "address": ip}])]


def _metadata_get(url: str, headers: dict, timeout: float) -> str:
    import urllib.request
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode()


def _probe_cloud(node: Node, cfg: dict, name: str, base: str,
                 headers: dict, gate: str, keys: list) -> bool:
    """Shared metadata prober for the per-cloud fingerprinters. The GATE
    key must answer (that's the platform-detection signal, ref
    env_aws.go isAWS / env_gce.go isGCE); remaining keys are collected
    best-effort, each behind the same short timeout. Returns detected."""
    if node.attributes.get("platform"):
        return False                     # an earlier cloud already won
    get = cfg.get("metadata_get", _metadata_get)
    timeout = float(cfg.get("metadata_timeout", 0.2))
    try:
        gate_val = get(base + gate, headers, timeout).strip()
    except Exception:                    # noqa: BLE001 — not on this cloud
        return False
    collected = {}
    for path, attr in keys:
        if path == gate:
            collected[attr] = gate_val
            continue
        try:
            collected[attr] = get(base + path, headers, timeout).strip()
        # absent metadata keys are the NORMAL case off-cloud
        except Exception:  # nomadlint: disable=EXC001 — probe, absent is fine
            pass
    node.attributes.update(collected)
    node.attributes["platform"] = name
    return True


def fp_env_aws(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/env_aws.go: EC2 IMDS attribute set."""
    keys = [
        ("instance-type", "platform.aws.instance-type"),
        ("ami-id", "platform.aws.ami-id"),
        ("placement/availability-zone",
         "platform.aws.placement.availability-zone"),
        ("local-ipv4", "unique.platform.aws.local-ipv4"),
        ("local-hostname", "unique.platform.aws.local-hostname"),
        ("public-ipv4", "unique.platform.aws.public-ipv4"),
        ("public-hostname", "unique.platform.aws.public-hostname"),
        ("mac", "unique.platform.aws.mac"),
        ("instance-life-cycle", "platform.aws.instance-life-cycle"),
    ]
    _probe_cloud(node, cfg, "aws",
                 "http://169.254.169.254/latest/meta-data/", {},
                 "instance-type", keys)


def fp_env_gce(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/env_gce.go: GCE metadata attribute set."""
    keys = [
        ("machine-type", "platform.gce.machine-type"),
        ("zone", "platform.gce.zone"),
        ("hostname", "unique.platform.gce.hostname"),
        ("id", "unique.platform.gce.id"),
        ("network-interfaces/0/ip", "unique.platform.gce.network.ip"),
        ("network-interfaces/0/access-configs/0/external-ip",
         "unique.platform.gce.network.external-ip"),
        ("scheduling/automatic-restart", "platform.gce.scheduling.automatic-restart"),
        ("scheduling/preemptible", "platform.gce.scheduling.preemptible"),
    ]
    _probe_cloud(node, cfg, "gce",
                 "http://169.254.169.254/computeMetadata/v1/instance/",
                 {"Metadata-Flavor": "Google"}, "machine-type", keys)


def fp_env_azure(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/env_azure.go: Azure IMDS attribute set."""
    q = "?api-version=2019-06-04&format=text"
    keys = [
        ("vmSize" + q, "platform.azure.compute.vm-size"),
        ("location" + q, "platform.azure.compute.location"),
        ("name" + q, "unique.platform.azure.compute.name"),
        ("resourceGroupName" + q,
         "platform.azure.compute.resource-group-name"),
        ("vmId" + q, "unique.platform.azure.compute.vm-id"),
        ("zone" + q, "platform.azure.compute.zone"),
        ("vmScaleSetName" + q, "platform.azure.compute.scale-set-name"),
    ]
    _probe_cloud(node, cfg, "azure",
                 "http://169.254.169.254/metadata/instance/compute/",
                 {"Metadata": "true"}, "vmSize" + q, keys)


def fp_cni(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/cni.go: scan the CNI config dir for
    .conf/.conflist networks -> plugins.cni.network.<name>."""
    import json as _json
    cni_dir = cfg.get("cni_config_dir", "/opt/cni/config")
    if not os.path.isdir(cni_dir):
        return
    for fn in sorted(os.listdir(cni_dir)):
        if not (fn.endswith(".conf") or fn.endswith(".conflist")
                or fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(cni_dir, fn)) as f:
                conf = _json.load(f)
        except (OSError, ValueError):
            continue
        name = conf.get("name")
        if name:
            node.attributes[f"plugins.cni.network.{name}"] = \
                str(conf.get("cniVersion", "unknown"))


def fp_os(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/host.go os.name/os.version via os-release."""
    try:
        with open("/etc/os-release") as f:
            kv = dict(line.strip().split("=", 1)
                      for line in f if "=" in line)
    except OSError:
        return
    name = kv.get("ID", kv.get("NAME", "")).strip('"')
    version = kv.get("VERSION_ID", "").strip('"')
    if name:
        node.attributes["os.name"] = name
    if version:
        node.attributes["os.version"] = version


def fp_virtual(node: Node, cfg: dict) -> None:
    """Virtualization detection (ref client/fingerprint: the reference
    tags cloud instances via env_*; the generic host analog reads DMI +
    the cpu hypervisor flag)."""
    try:
        with open("/sys/class/dmi/id/product_name") as f:
            product = f.read().strip()
        if product:
            node.attributes["unique.platform.product-name"] = product
    except OSError:
        pass
    try:
        with open("/proc/cpuinfo") as f:
            if " hypervisor" in f.read():
                node.attributes["cpu.arch.virtual"] = "true"
                node.attributes["virtualization"] = "guest"
    except OSError:
        pass


def fp_consul(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/consul.go: probe the local Consul agent
    (or the analog service-catalog endpoint) and tag its presence."""
    addr = cfg.get("consul_addr", os.environ.get(
        "CONSUL_HTTP_ADDR", "http://127.0.0.1:8500"))
    try:
        body = _metadata_get(addr.rstrip("/") + "/v1/agent/self", {}, 0.5)
    except Exception:       # noqa: BLE001 — absent is the common case
        return
    node.attributes["consul.available"] = "true"
    import json as _json
    try:
        info = _json.loads(body)
        node.attributes["consul.version"] = \
            info.get("Config", {}).get("Version", "")
        node.attributes["consul.datacenter"] = \
            info.get("Config", {}).get("Datacenter", "")
    except ValueError:
        pass


def fp_vault(node: Node, cfg: dict) -> None:
    """ref client/fingerprint/vault.go: probe the Vault (analog
    secrets provider) health endpoint."""
    addr = cfg.get("vault_addr", os.environ.get("VAULT_ADDR", ""))
    if not addr:
        return
    try:
        _metadata_get(addr.rstrip("/") + "/v1/sys/health", {}, 0.5)
    except Exception:       # noqa: BLE001
        return
    node.attributes["vault.accessible"] = "true"


FINGERPRINTERS = [
    ("arch", fp_arch),
    ("cpu", fp_cpu),
    ("memory", fp_memory),
    ("storage", fp_storage),
    ("host", fp_host),
    ("os", fp_os),
    ("virtual", fp_virtual),
    ("nomad", fp_nomad),
    ("signal", fp_signal),
    ("cgroup", fp_cgroup),
    ("bridge", fp_bridge),
    ("network", fp_network),
    ("env_aws", fp_env_aws),
    ("env_gce", fp_env_gce),
    ("env_azure", fp_env_azure),
    ("cni", fp_cni),
    ("consul", fp_consul),
    ("vault", fp_vault),
]


def fingerprint_node(data_dir: str = "/tmp", datacenter: str = "dc1",
                     node_class: str = "", name: str = "",
                     node_id: str = "", cfg: dict | None = None) -> Node:
    """Assemble a Node by running every fingerprinter (ref
    client/fingerprint_manager.go + client.go:1462
    updateNodeFromFingerprint)."""
    cfg = dict(cfg or {})
    cfg.setdefault("data_dir", data_dir)
    node = Node(
        id=node_id or str(uuid.uuid4()),
        name=name or platform.node() or "node",
        datacenter=datacenter,
        node_class=node_class,
        node_resources=NodeResources(),
    )
    for fp_name, fp in FINGERPRINTERS:
        try:
            fp(node, cfg)
        # a fingerprinter that can't detect its facet just contributes
        # nothing; the node registers with what the others found
        except Exception:  # nomadlint: disable=EXC001 — probe, absent is fine
            pass
    return node


def fingerprint_drivers(drivers: dict) -> dict:
    """Driver fingerprints -> node.drivers + attributes
    (ref pluginmanager/drivermanager)."""
    out = {}
    for name, driver in drivers.items():
        out[name] = driver.fingerprint()
    return out
