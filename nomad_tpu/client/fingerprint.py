"""Host fingerprinting (ref client/fingerprint/ — arch, cpu, memory,
storage, network, host, nomad version — one fingerprinter per concern,
merged into the Node)."""
from __future__ import annotations

import os
import platform
import shutil
import socket
import uuid

from ..structs import (
    NetworkResource, Node, NodeCpuResources, NodeDiskResources,
    NodeMemoryResources, NodeNetworkResource, NodeResources,
)
from .. import __version__


def _cpu_mhz_total() -> tuple[int, int]:
    """(total MHz across cores, core count)"""
    cores = os.cpu_count() or 1
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except OSError:
        pass
    return int(mhz * cores), cores


def _memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 1024


def _disk_mb(path: str) -> int:
    try:
        usage = shutil.disk_usage(path)
        return usage.free // (1024 * 1024)
    except OSError:
        return 10 * 1024


def _host_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def fingerprint_node(data_dir: str = "/tmp", datacenter: str = "dc1",
                     node_class: str = "", name: str = "",
                     node_id: str = "") -> Node:
    """Assemble a Node from host fingerprints (ref
    client/fingerprint_manager.go + client.go:1462
    updateNodeFromFingerprint)."""
    cpu_mhz, cores = _cpu_mhz_total()
    ip = _host_ip()
    node = Node(
        id=node_id or str(uuid.uuid4()),
        name=name or platform.node() or "node",
        datacenter=datacenter,
        node_class=node_class,
        attributes={
            "kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "arch": platform.machine(),
            "os.name": platform.system().lower(),
            "cpu.numcores": str(cores),
            "cpu.totalcompute": str(cpu_mhz),
            "memory.totalbytes": str(_memory_mb() * 1024 * 1024),
            "nomad.version": __version__,
            "unique.hostname": platform.node(),
            "unique.network.ip-address": ip,
        },
        node_resources=NodeResources(
            cpu=NodeCpuResources(cpu_shares=cpu_mhz, total_core_count=cores,
                                 reservable_cores=list(range(cores))),
            memory=NodeMemoryResources(memory_mb=_memory_mb()),
            disk=NodeDiskResources(disk_mb=_disk_mb(data_dir)),
            networks=[NetworkResource(device="eth0", ip=ip,
                                      cidr=f"{ip}/32", mbits=1000)],
            node_networks=[NodeNetworkResource(
                mode="host", device="eth0", speed=1000,
                addresses=[{"alias": "default", "address": ip}])],
        ),
    )
    return node


def fingerprint_drivers(drivers: dict) -> dict:
    """Driver fingerprints -> node.drivers + attributes
    (ref pluginmanager/drivermanager)."""
    out = {}
    for name, driver in drivers.items():
        out[name] = driver.fingerprint()
    return out
