"""Extended task drivers: java, qemu, docker (ref drivers/java/driver.go,
drivers/qemu/driver.go, drivers/docker/driver.go).

Each follows the reference's shape: fingerprint gates on the host runtime
being present (java binary, qemu binary, docker socket+CLI), start builds
the runtime-specific command line, and lifecycle is managed through the
same process supervision the raw_exec driver uses (the reference routes
java/qemu through its shared executor the same way; docker drives the
engine, here via the docker CLI instead of the HTTP API client library).
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import time
from typing import Optional

from ..structs import DriverInfo
from .driver import ExitResult, RawExecDriver, TaskHandle


def _binary_version(cmd: list[str]) -> Optional[str]:
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=5)
        text = (out.stdout or out.stderr).decode(errors="replace")
        return text.splitlines()[0].strip() if text else ""
    except (OSError, subprocess.TimeoutExpired, IndexError):
        return None


class JavaDriver(RawExecDriver):
    """ref drivers/java: config keys jar_path | class, args, jvm_options."""

    name = "java"

    def fingerprint(self) -> DriverInfo:
        if shutil.which("java") is None:
            return DriverInfo(detected=False, healthy=False,
                              health_description="java binary not found")
        version = _binary_version(["java", "-version"]) or ""
        return DriverInfo(detected=True, healthy=True,
                          attributes={"driver.java.version": version})

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        argv = ["java"]
        jvm_options = cfg.get("jvm_options", [])
        if isinstance(jvm_options, str):
            jvm_options = shlex.split(jvm_options)
        argv += list(jvm_options)
        if task.resources.memory_mb:
            argv.append(f"-Xmx{task.resources.memory_mb}m")
        if cfg.get("jar_path"):
            argv += ["-jar", cfg["jar_path"]]
        elif cfg.get("class"):
            if cfg.get("class_path"):
                argv += ["-cp", cfg["class_path"]]
            argv.append(cfg["class"])
        else:
            raise ValueError("java driver requires jar_path or class")
        args = cfg.get("args", [])
        if isinstance(args, str):
            args = shlex.split(args)
        argv += list(args)
        # delegate supervision to the raw_exec machinery
        wrapped = task.copy()
        wrapped.config = {"command": argv[0], "args": argv[1:]}
        return super().start_task(task_id, wrapped, task_dir, env)


class QemuDriver(RawExecDriver):
    """ref drivers/qemu: config keys image_path, accelerator, graceful
    shutdown via monitor is simplified to SIGTERM; port_map -> hostfwd."""

    name = "qemu"
    binary = "qemu-system-x86_64"

    def fingerprint(self) -> DriverInfo:
        if shutil.which(self.binary) is None:
            return DriverInfo(detected=False, healthy=False,
                              health_description="qemu binary not found")
        version = _binary_version([self.binary, "--version"]) or ""
        return DriverInfo(detected=True, healthy=True,
                          attributes={"driver.qemu.version": version})

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        image = cfg.get("image_path", "")
        if not image:
            raise ValueError("qemu driver requires image_path")
        if not os.path.isabs(image):
            image = os.path.join(task_dir, image)
        argv = [self.binary,
                "-machine", f"type=pc,accel={cfg.get('accelerator', 'tcg')}",
                "-name", task.name,
                "-m", f"{task.resources.memory_mb or 512}M",
                "-drive", f"file={image}",
                "-nographic"]
        for fwd in cfg.get("port_map", []):
            host, guest = fwd.get("host", 0), fwd.get("guest", 0)
            argv += ["-netdev",
                     f"user,id=n{host},hostfwd=tcp::{host}-:{guest}",
                     "-device", f"virtio-net,netdev=n{host}"]
        extra = cfg.get("args", [])
        if isinstance(extra, str):
            extra = shlex.split(extra)
        argv += list(extra)
        wrapped = task.copy()
        wrapped.config = {"command": argv[0], "args": argv[1:]}
        return super().start_task(task_id, wrapped, task_dir, env)


class DockerDriver:
    """ref drivers/docker: engine lifecycle via the docker CLI — run with
    labels/resource limits, stop with configurable timeout, logs captured
    through `docker logs` into the task log files."""

    name = "docker"

    def __init__(self, docker_bin: str = "docker"):
        self.docker_bin = docker_bin
        self._containers: dict[str, dict] = {}

    # ------------------------------------------------------------ plumbing

    def _docker(self, *args, timeout: float = 30.0) -> subprocess.CompletedProcess:
        return subprocess.run([self.docker_bin, *args],
                              capture_output=True, timeout=timeout)

    def available(self) -> bool:
        if shutil.which(self.docker_bin) is None:
            return False
        try:
            return self._docker("version", "--format", "{{.Server.Version}}",
                                timeout=5).returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            return False

    def fingerprint(self) -> DriverInfo:
        if not self.available():
            return DriverInfo(detected=False, healthy=False,
                              health_description="docker daemon unavailable")
        version = self._docker("version", "--format",
                               "{{.Server.Version}}").stdout.decode().strip()
        return DriverInfo(detected=True, healthy=True,
                          attributes={"driver.docker.version": version})

    # ----------------------------------------------------------- lifecycle

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        image = cfg.get("image", "")
        if not image:
            raise ValueError("docker driver requires config.image")
        cname = "nomad-" + task_id.replace("/", "-")
        argv = ["run", "-d", "--name", cname,
                "--label", f"nomad_task_id={task_id}"]
        if task.resources.memory_mb:
            argv += ["--memory", f"{task.resources.memory_mb}m"]
        if task.resources.cpu:
            argv += ["--cpu-shares", str(task.resources.cpu)]
        for k, v in env.items():
            argv += ["-e", f"{k}={v}"]
        for vol in cfg.get("volumes", []):
            argv += ["-v", vol]
        for port in cfg.get("ports", []):
            argv += ["-p", str(port)]
        argv.append(image)
        command = cfg.get("command", "")
        if command:
            argv.append(command)
            args = cfg.get("args", [])
            if isinstance(args, str):
                args = shlex.split(args)
            argv += list(args)
        out = self._docker(*argv, timeout=120.0)
        if out.returncode != 0:
            raise RuntimeError(
                f"docker run failed: {out.stderr.decode(errors='replace')}")
        container_id = out.stdout.decode().strip()
        self._containers[task_id] = {
            "id": container_id, "name": cname, "task_dir": task_dir,
            "task_name": task.name,
        }
        return TaskHandle(task_id=task_id, driver=self.name,
                          config={"container_id": container_id,
                                  "name": cname},
                          started_at=time.time())

    def wait_task(self, task_id, timeout=None):
        rec = self._containers.get(task_id)
        if rec is None:
            return ExitResult(err="unknown task")
        try:
            out = self._docker("wait", rec["id"],
                               timeout=timeout if timeout else 86400.0)
        except subprocess.TimeoutExpired:
            return None
        if out.returncode != 0:
            return ExitResult(err=out.stderr.decode(errors="replace"))
        self._collect_logs(rec)
        try:
            return ExitResult(exit_code=int(out.stdout.decode().strip()))
        except ValueError:
            return ExitResult(err="unparseable docker wait output")

    def _collect_logs(self, rec: dict) -> None:
        out = self._docker("logs", rec["id"])
        try:
            base = os.path.join(rec["task_dir"], rec["task_name"])
            with open(f"{base}.stdout.log", "ab") as f:
                f.write(out.stdout)
            with open(f"{base}.stderr.log", "ab") as f:
                f.write(out.stderr)
        except OSError:
            pass

    def stop_task(self, task_id, kill_timeout=5.0, sig=""):
        rec = self._containers.get(task_id)
        if rec is None:
            return
        self._docker("stop", "-t", str(int(kill_timeout)), rec["id"],
                     timeout=kill_timeout + 30.0)

    def destroy_task(self, task_id):
        rec = self._containers.pop(task_id, None)
        if rec is not None:
            self._docker("rm", "-f", rec["id"])

    def signal_task(self, task_id, sig):
        rec = self._containers.get(task_id)
        if rec is None:
            raise ValueError("unknown task")
        out = self._docker("kill", "--signal", sig, rec["id"])
        if out.returncode != 0:
            raise ValueError(out.stderr.decode(errors="replace"))

    def task_stats(self, task_id):
        rec = self._containers.get(task_id)
        if rec is None:
            return {"cpu_percent": 0.0, "memory_rss_bytes": 0}
        out = self._docker("stats", "--no-stream", "--format",
                           "{{.CPUPerc}} {{.MemUsage}}", rec["id"])
        try:
            cpu, mem = out.stdout.decode().split()[:2]
            return {"cpu_percent": float(cpu.rstrip("%")),
                    "memory_rss_bytes": _parse_size(mem)}
        except (ValueError, IndexError):
            return {"cpu_percent": 0.0, "memory_rss_bytes": 0}

    def inspect_task(self, task_id):
        rec = self._containers.get(task_id)
        if rec is None:
            return None
        return TaskHandle(task_id=task_id, driver=self.name,
                          config={"container_id": rec["id"]})

    def recover_task(self, handle):
        cid = handle.config.get("container_id", "")
        if not cid:
            return False
        out = self._docker("inspect", "--format", "{{.State.Running}}", cid)
        if out.returncode != 0 or b"true" not in out.stdout:
            return False
        self._containers[handle.task_id] = {
            "id": cid, "name": handle.config.get("name", ""),
            "task_dir": "", "task_name": ""}
        return True


def _parse_size(s: str) -> int:
    """'12.5MiB' -> bytes"""
    units = {"B": 1, "KiB": 1 << 10, "MiB": 1 << 20, "GiB": 1 << 30,
             "kB": 1000, "MB": 1000**2, "GB": 1000**3}
    for unit, mult in sorted(units.items(), key=lambda kv: -len(kv[0])):
        if s.endswith(unit):
            try:
                return int(float(s[:-len(unit)]) * mult)
            except ValueError:
                return 0
    return 0
