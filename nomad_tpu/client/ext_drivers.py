"""Extended task drivers: java, qemu, docker (ref drivers/java/driver.go,
drivers/qemu/driver.go, drivers/docker/driver.go).

Each follows the reference's shape: fingerprint gates on the host runtime
being present (java binary, qemu binary, docker socket+CLI), start builds
the runtime-specific command line, and lifecycle is managed through the
same process supervision the raw_exec driver uses (the reference routes
java/qemu through its shared executor the same way; docker drives the
engine, here via the docker CLI instead of the HTTP API client library).
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import time
from typing import Optional

from ..structs import DriverInfo
from .driver import ExitResult, RawExecDriver, TaskHandle


def _binary_version(cmd: list[str]) -> Optional[str]:
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=5)
        text = (out.stdout or out.stderr).decode(errors="replace")
        return text.splitlines()[0].strip() if text else ""
    except (OSError, subprocess.TimeoutExpired, IndexError):
        return None


class JavaDriver(RawExecDriver):
    """ref drivers/java: config keys jar_path | class, args, jvm_options."""

    name = "java"

    def config_schema(self):
        # overrides the inherited raw_exec schema, which would reject
        # every legitimate java config key
        return {"jar_path": {"type": "string"},
                "class": {"type": "string"},
                "class_path": {"type": "string"},
                "jvm_options": {"type": "list"},
                "args": {"type": "list_or_string"}}

    def fingerprint(self) -> DriverInfo:
        if shutil.which("java") is None:
            return DriverInfo(detected=False, healthy=False,
                              health_description="java binary not found")
        version = _binary_version(["java", "-version"]) or ""
        return DriverInfo(detected=True, healthy=True,
                          attributes={"driver.java.version": version})

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        argv = ["java"]
        jvm_options = cfg.get("jvm_options", [])
        if isinstance(jvm_options, str):
            jvm_options = shlex.split(jvm_options)
        argv += list(jvm_options)
        if task.resources.memory_mb:
            argv.append(f"-Xmx{task.resources.memory_mb}m")
        if cfg.get("jar_path"):
            argv += ["-jar", cfg["jar_path"]]
        elif cfg.get("class"):
            if cfg.get("class_path"):
                argv += ["-cp", cfg["class_path"]]
            argv.append(cfg["class"])
        else:
            raise ValueError("java driver requires jar_path or class")
        args = cfg.get("args", [])
        if isinstance(args, str):
            args = shlex.split(args)
        argv += list(args)
        # delegate supervision to the raw_exec machinery
        wrapped = task.copy()
        wrapped.config = {"command": argv[0], "args": argv[1:]}
        return super().start_task(task_id, wrapped, task_dir, env)


class QemuDriver(RawExecDriver):
    """ref drivers/qemu: config keys image_path, accelerator, graceful
    shutdown via monitor is simplified to SIGTERM; port_map -> hostfwd."""

    name = "qemu"
    binary = "qemu-system-x86_64"

    def config_schema(self):
        return {"image_path": {"type": "string", "required": True},
                "accelerator": {"type": "string"},
                "port_map": {"type": "list"},
                "args": {"type": "list_or_string"}}

    def fingerprint(self) -> DriverInfo:
        if shutil.which(self.binary) is None:
            return DriverInfo(detected=False, healthy=False,
                              health_description="qemu binary not found")
        version = _binary_version([self.binary, "--version"]) or ""
        return DriverInfo(detected=True, healthy=True,
                          attributes={"driver.qemu.version": version})

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        image = cfg.get("image_path", "")
        if not image:
            raise ValueError("qemu driver requires image_path")
        if not os.path.isabs(image):
            image = os.path.join(task_dir, image)
        argv = [self.binary,
                "-machine", f"type=pc,accel={cfg.get('accelerator', 'tcg')}",
                "-name", task.name,
                "-m", f"{task.resources.memory_mb or 512}M",
                "-drive", f"file={image}",
                "-nographic"]
        for fwd in cfg.get("port_map", []):
            host, guest = fwd.get("host", 0), fwd.get("guest", 0)
            argv += ["-netdev",
                     f"user,id=n{host},hostfwd=tcp::{host}-:{guest}",
                     "-device", f"virtio-net,netdev=n{host}"]
        extra = cfg.get("args", [])
        if isinstance(extra, str):
            extra = shlex.split(extra)
        argv += list(extra)
        wrapped = task.copy()
        wrapped.config = {"command": argv[0], "args": argv[1:]}
        return super().start_task(task_id, wrapped, task_dir, env)


class ImageCoordinator:
    """Refcounted image pulls (ref drivers/docker/coordinator.go
    dockerCoordinator): concurrent tasks asking for the same image share
    ONE pull (per-image lock, others wait on it); each task holds a
    reference, and when the last reference drops the image is removed —
    if cleanup is enabled — after a delay that lets rapid reschedules
    reuse the warm image."""

    def __init__(self, pull_fn, remove_fn, cleanup: bool = False,
                 remove_delay: float = 0.0):
        import threading
        self._pull = pull_fn
        self._remove = remove_fn
        self.cleanup = cleanup
        self.remove_delay = remove_delay
        self._lock = threading.Lock()
        self._pulls: dict[str, threading.Event] = {}    # in-flight
        self._pull_err: dict[str, str] = {}
        self._refs: dict[str, set] = {}                 # image -> task ids
        self._remove_timers: dict[str, object] = {}     # delayed removes
        self.stats = {"pulls": 0, "pull_waits": 0, "removes": 0}

    def pull(self, image: str, task_id: str) -> None:
        import threading
        while True:
            with self._lock:
                # a re-reference cancels any pending delayed remove (ref
                # coordinator.go: IncrementImageReference stops the
                # removal timer) — otherwise the timer fires into the
                # new user's warm-reuse window
                timer = self._remove_timers.pop(image, None)
                if timer is not None:
                    timer.cancel()
                inflight = self._pulls.get(image)
                if inflight is None:
                    if image in self._refs:              # already present
                        self._refs[image].add(task_id)
                        return
                    ev = self._pulls[image] = threading.Event()
                    self.stats["pulls"] += 1
                    leader = True
                else:
                    ev = inflight
                    leader = False
                    self.stats["pull_waits"] += 1
            if leader:
                try:
                    self._pull(image)
                    with self._lock:
                        self._refs[image] = {task_id}
                        self._pull_err.pop(image, None)
                except Exception as e:  # noqa: BLE001
                    with self._lock:
                        self._pull_err[image] = str(e)
                finally:
                    with self._lock:
                        self._pulls.pop(image, None)
                    ev.set()
                err = self._pull_err.get(image)
                if err:
                    raise RuntimeError(f"image pull failed: {err}")
                return
            ev.wait(timeout=600.0)
            with self._lock:
                err = self._pull_err.get(image)
                if err is None and image in self._refs:
                    self._refs[image].add(task_id)
                    return
            if err:
                raise RuntimeError(f"image pull failed: {err}")
            # leader failed or raced a remove: retry as a fresh leader

    def release(self, image: str, task_id: str) -> None:
        """ref coordinator.go RemoveImage: drop the task's reference;
        remove the image when the last reference goes (cleanup on)."""
        import threading
        with self._lock:
            refs = self._refs.get(image)
            if refs is None:
                return
            refs.discard(task_id)
            if refs or not self.cleanup:
                return
            self._refs.pop(image, None)

        def _do_remove():
            with self._lock:
                self._remove_timers.pop(image, None)
                # re-referenced since scheduling, or a fresh pull is
                # in flight (leader sets _refs only after the pull
                # returns) — either way the image is wanted again
                if image in self._refs or image in self._pulls:
                    return
            try:
                self._remove(image)
                self.stats["removes"] += 1
            # in-use images legitimately refuse removal; the next GC
            # pass retries once the refcount drops
            except Exception:  # nomadlint: disable=EXC001 — GC retries
                pass
        if self.remove_delay > 0:
            t = threading.Timer(self.remove_delay, _do_remove)
            with self._lock:
                self._remove_timers[image] = t
            t.start()
        else:
            _do_remove()


class DockerDriver:
    """ref drivers/docker: engine lifecycle via the docker CLI — run with
    labels/resource limits, refcount-coordinated image pulls, port maps
    from the scheduler's allocated host ports, stop with configurable
    timeout, `docker exec` sessions, logs captured through `docker logs`
    into the task log files."""

    name = "docker"

    def __init__(self, docker_bin: str = "docker",
                 image_cleanup: bool = False,
                 image_remove_delay: float = 0.0):
        self.docker_bin = docker_bin
        self._containers: dict[str, dict] = {}
        self.coordinator = ImageCoordinator(
            self._pull_image, self._remove_image,
            cleanup=image_cleanup, remove_delay=image_remove_delay)

    def _pull_image(self, image: str) -> None:
        out = self._docker("pull", image, timeout=600.0)
        if out.returncode != 0:
            raise RuntimeError(out.stderr.decode(errors="replace"))

    def _remove_image(self, image: str) -> None:
        self._docker("rmi", image)

    # ------------------------------------------------------------ plumbing

    def _docker(self, *args, timeout: float = 30.0) -> subprocess.CompletedProcess:
        return subprocess.run([self.docker_bin, *args],
                              capture_output=True, timeout=timeout)

    def available(self) -> bool:
        if shutil.which(self.docker_bin) is None:
            return False
        try:
            return self._docker("version", "--format", "{{.Server.Version}}",
                                timeout=5).returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            return False

    def fingerprint(self) -> DriverInfo:
        if not self.available():
            return DriverInfo(detected=False, healthy=False,
                              health_description="docker daemon unavailable")
        version = self._docker("version", "--format",
                               "{{.Server.Version}}").stdout.decode().strip()
        return DriverInfo(detected=True, healthy=True,
                          attributes={"driver.docker.version": version})

    # ----------------------------------------------------------- lifecycle

    def start_task(self, task_id, task, task_dir, env):
        cfg = task.config
        image = cfg.get("image", "")
        if not image:
            raise ValueError("docker driver requires config.image")
        # coordinated pull: N tasks of one job pulling the same image on
        # one node share a single `docker pull` (ref coordinator.go)
        if not cfg.get("skip_pull"):
            self.coordinator.pull(image, task_id)
        cname = "nomad-" + task_id.replace("/", "-")
        argv = ["run", "-d", "--name", cname,
                "--label", f"nomad_task_id={task_id}"]
        if task.resources.memory_mb:
            argv += ["--memory", f"{task.resources.memory_mb}m"]
        if task.resources.cpu:
            argv += ["--cpu-shares", str(task.resources.cpu)]
        if cfg.get("network_mode"):
            argv += ["--network", cfg["network_mode"]]
        for dns in cfg.get("dns_servers", []):
            argv += ["--dns", dns]
        if cfg.get("work_dir"):
            argv += ["-w", cfg["work_dir"]]
        if cfg.get("entrypoint"):
            argv += ["--entrypoint", cfg["entrypoint"]]
        for k, v in env.items():
            argv += ["-e", f"{k}={v}"]
        for vol in cfg.get("volumes", []):
            argv += ["-v", vol]
        for port in cfg.get("ports", []):
            argv += ["-p", str(port)]
        # port_map {label: container_port}: bind the scheduler-allocated
        # host port (from the task env) to the container port (ref
        # drivers/docker port mapping off AllocatedPorts)
        for label, cport in (cfg.get("port_map", {}) or {}).items():
            hp = env.get(f"NOMAD_HOST_PORT_{label}") or \
                env.get(f"NOMAD_PORT_{label}")
            if hp:
                argv += ["-p", f"{hp}:{cport}"]
        argv.append(image)
        command = cfg.get("command", "")
        if command:
            argv.append(command)
            args = cfg.get("args", [])
            if isinstance(args, str):
                args = shlex.split(args)
            argv += list(args)
        try:
            out = self._docker(*argv, timeout=120.0)
        except Exception:
            # a hung daemon (TimeoutExpired/OSError) must still drop the
            # image reference or the refcount never reaches zero
            self.coordinator.release(image, task_id)
            raise
        if out.returncode != 0:
            self.coordinator.release(image, task_id)
            raise RuntimeError(
                f"docker run failed: {out.stderr.decode(errors='replace')}")
        container_id = out.stdout.decode().strip()
        self._containers[task_id] = {
            "id": container_id, "name": cname, "task_dir": task_dir,
            "task_name": task.name, "image": image,
        }
        return TaskHandle(task_id=task_id, driver=self.name,
                          config={"container_id": container_id,
                                  "name": cname, "image": image},
                          started_at=time.time())

    def wait_task(self, task_id, timeout=None):
        rec = self._containers.get(task_id)
        if rec is None:
            return ExitResult(err="unknown task")
        try:
            out = self._docker("wait", rec["id"],
                               timeout=timeout if timeout else 86400.0)
        except subprocess.TimeoutExpired:
            return None
        if out.returncode != 0:
            return ExitResult(err=out.stderr.decode(errors="replace"))
        self._collect_logs(rec)
        try:
            return ExitResult(exit_code=int(out.stdout.decode().strip()))
        except ValueError:
            return ExitResult(err="unparseable docker wait output")

    def _collect_logs(self, rec: dict) -> None:
        out = self._docker("logs", rec["id"])
        try:
            base = os.path.join(rec["task_dir"], rec["task_name"])
            # docker log capture: loss-tolerant stream data,
            # re-fetchable from the daemon
            # nomadlint: disable=DUR001 — loss-tolerant log stream
            with open(f"{base}.stdout.log", "ab") as f:
                f.write(out.stdout)
            # nomadlint: disable=DUR001 — docker log capture, see above
            with open(f"{base}.stderr.log", "ab") as f:
                f.write(out.stderr)
        except OSError:
            pass

    def stop_task(self, task_id, kill_timeout=5.0, sig=""):
        rec = self._containers.get(task_id)
        if rec is None:
            return
        self._docker("stop", "-t", str(int(kill_timeout)), rec["id"],
                     timeout=kill_timeout + 30.0)

    def destroy_task(self, task_id):
        rec = self._containers.pop(task_id, None)
        if rec is not None:
            self._docker("rm", "-f", rec["id"])
            if rec.get("image"):
                self.coordinator.release(rec["image"], task_id)

    def exec_task(self, task_id, command, tty: bool = False, cwd: str = "",
                  env=None):
        """`docker exec` session (ref drivers/docker ExecTaskStreaming)."""
        from .driver import ExecSession
        rec = self._containers.get(task_id)
        if rec is None:
            raise ValueError("unknown task")
        argv = [self.docker_bin, "exec", "-i"]
        if tty:
            argv.append("-t")
        if cwd:
            argv += ["-w", cwd]
        for k, v in (env or {}).items():
            argv += ["-e", f"{k}={v}"]
        argv.append(rec["id"])
        argv += list(command or [])
        return ExecSession(argv, cwd=os.getcwd(), env=dict(os.environ),
                           tty=tty)

    def signal_task(self, task_id, sig):
        rec = self._containers.get(task_id)
        if rec is None:
            raise ValueError("unknown task")
        out = self._docker("kill", "--signal", sig, rec["id"])
        if out.returncode != 0:
            raise ValueError(out.stderr.decode(errors="replace"))

    def task_stats(self, task_id):
        rec = self._containers.get(task_id)
        if rec is None:
            return {"cpu_percent": 0.0, "memory_rss_bytes": 0}
        out = self._docker("stats", "--no-stream", "--format",
                           "{{.CPUPerc}} {{.MemUsage}}", rec["id"])
        try:
            cpu, mem = out.stdout.decode().split()[:2]
            return {"cpu_percent": float(cpu.rstrip("%")),
                    "memory_rss_bytes": _parse_size(mem)}
        except (ValueError, IndexError):
            return {"cpu_percent": 0.0, "memory_rss_bytes": 0}

    def inspect_task(self, task_id):
        rec = self._containers.get(task_id)
        if rec is None:
            return None
        return TaskHandle(task_id=task_id, driver=self.name,
                          config={"container_id": rec["id"]})

    def recover_task(self, handle):
        cid = handle.config.get("container_id", "")
        if not cid:
            return False
        out = self._docker("inspect", "--format", "{{.State.Running}}", cid)
        if out.returncode != 0 or b"true" not in out.stdout:
            return False
        self._containers[handle.task_id] = {
            "id": cid, "name": handle.config.get("name", ""),
            "task_dir": "", "task_name": "",
            "image": handle.config.get("image", "")}
        return True


def _parse_size(s: str) -> int:
    """'12.5MiB' -> bytes"""
    units = {"B": 1, "KiB": 1 << 10, "MiB": 1 << 20, "GiB": 1 << 30,
             "kB": 1000, "MB": 1000**2, "GB": 1000**3}
    for unit, mult in sorted(units.items(), key=lambda kv: -len(kv[0])):
        if s.endswith(unit):
            try:
                return int(float(s[:-len(unit)]) * mult)
            except ValueError:
                return 0
    return 0
