"""TaskRunner: one task's lifecycle (ref
client/allocrunner/taskrunner/task_runner.go:480 Run, restart logic :738,
restoreHandle :1129).

Loop: prestart hooks (dirs, env, artifacts/templates as stubs) -> driver
start -> wait -> restart policy (attempts within interval, delay,
mode fail|delay) -> terminal state. Task events accumulate on TaskState.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from ..structs import (
    Task, TaskEvent, TaskState, TASK_STATE_DEAD, TASK_STATE_PENDING,
    TASK_STATE_RUNNING,
)
from .driver import Driver, ExitResult, TaskHandle

EVENT_RECEIVED = "Received"
EVENT_TASK_SETUP = "Task Setup"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"
EVENT_SIGNALING = "Signaling"
EVENT_RESTART_SIGNAL = "Restart Signaled"


class TaskRunner:
    def __init__(self, alloc, task: Task, driver: Driver, task_dir: str,
                 env: dict[str, str],
                 on_state_change: Callable[[str, TaskState], None],
                 setup_error: str = "",
                 rendered_files: Optional[list] = None):
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.task_dir = task_dir
        self.env = env
        self.on_state_change = on_state_change

        self.state = TaskState()
        self.handle: Optional[TaskHandle] = None
        self._kill = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._restarts_in_window: list[float] = []
        self._restart_req = False
        self._logmon = None
        self.setup_error = setup_error   # pre-start hook failure (devices)
        # (relative_path, content, perms) written into the task dir at
        # setup: rendered templates, vault token (ref template/vault hooks)
        self.rendered_files = rendered_files or []

        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        self.restart_policy = tg.restart_policy if tg else None

    @property
    def task_id(self) -> str:
        return f"{self.alloc.id}/{self.task.name}"

    # ---------------------------------------------------------------- run

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"task-{self.task.name}")
        self._thread.start()

    def run(self) -> None:
        self._emit(EVENT_RECEIVED, "task received by client")
        if self.setup_error:
            # a failed pre-start hook (e.g. device reservation) fails the
            # task rather than launching it degraded (ref device_hook.go)
            self._fail(EVENT_TASK_SETUP, self.setup_error)
            return
        try:
            self._setup()
        except Exception as e:          # noqa: BLE001
            self._fail(EVENT_TASK_SETUP, f"setup failed: {e}")
            return
        while not self._kill.is_set():
            try:
                self.handle = self.driver.start_task(
                    self.task_id, self.task, self.task_dir, self.env)
            except Exception as e:      # noqa: BLE001
                if not self._should_restart(failed=True,
                                            reason=f"driver start: {e}"):
                    self._fail(EVENT_DRIVER_FAILURE, str(e))
                    return
                continue
            self._set_state(TASK_STATE_RUNNING, EVENT_STARTED,
                            "task started by client")
            result = self._wait_for_exit()
            if self._kill.is_set():
                self._emit(EVENT_KILLED, "task killed")
                self._finish(failed=False)
                return
            failed = result is None or not result.successful()
            code = result.exit_code if result else -1
            # between exit and restart the task is pending, not running —
            # deployment health must not count it as live
            self._set_state(TASK_STATE_PENDING, EVENT_TERMINATED,
                            f"exit code: {code}")
            if not self._should_restart(failed=failed,
                                        reason=f"exit {code}"):
                self._finish(failed=failed)
                return
        self._emit(EVENT_KILLED, "task killed")
        self._finish(failed=False)

    def _setup(self) -> None:
        os.makedirs(self.task_dir, exist_ok=True)
        os.makedirs(os.path.join(self.task_dir, "local"), exist_ok=True)
        os.makedirs(os.path.join(self.task_dir, "secrets"), exist_ok=True)
        for rel, content, perms in self.rendered_files:
            self.write_rendered_file(rel, content, perms)
        # log rotation per the task's log stanza (ref logmon_hook.go).
        # When THIS task's driver pipes output through the native
        # nomad-logmon sidecar, the sidecar owns rotation — running the
        # copy-truncate rotator on top would race its rename rotation.
        # Drivers that write files directly (exec's executor, docker's
        # log collection) still need the in-process rotator.
        from .logmon import LogRotator
        uses_sidecar = getattr(self.driver, "uses_logmon", None)
        if not (uses_sidecar is not None and uses_sidecar()):
            self._logmon = LogRotator(self.task_dir, self.task.name,
                                      self.task.log_config)
            self._logmon.start()

    def _wait_for_exit(self) -> Optional[ExitResult]:
        while not self._kill.is_set():
            result = self.driver.wait_task(self.task_id, timeout=0.2)
            if result is not None:
                return result
        # killed: stop the task
        self.driver.stop_task(self.task_id,
                              kill_timeout=self.task.kill_timeout_sec,
                              sig=self.task.kill_signal)
        return None

    # ------------------------------------------------------------ restarts

    def _should_restart(self, failed: bool, reason: str) -> bool:
        """ref taskrunner/restarts/restarts.go"""
        if self._restart_req and not self._kill.is_set():
            # user-initiated restart (alloc restart API): bypasses the
            # restart-policy accounting (ref restarts.go SetRestartTriggered)
            self._restart_req = False
            self._emit(EVENT_RESTARTING, "restarting: user requested")
            return True
        pol = self.restart_policy
        if pol is None or self._kill.is_set():
            return False
        if not failed and self.alloc.job is not None and \
           self.alloc.job.type == "service":
            # service tasks restart even on clean exit
            pass
        elif not failed:
            return False
        now = time.time()
        window_start = now - pol.interval_sec
        self._restarts_in_window = [t for t in self._restarts_in_window
                                    if t >= window_start]
        if len(self._restarts_in_window) >= pol.attempts:
            if pol.mode == "delay":
                self._emit(EVENT_RESTARTING,
                           f"exceeded attempts, delaying {pol.interval_sec}s")
                if self._kill.wait(pol.interval_sec):
                    return False
                self._restarts_in_window = []
            else:
                self._emit(EVENT_NOT_RESTARTING, "exceeded restart attempts")
                return False
        self._restarts_in_window.append(now)
        self.state.restarts += 1
        self.state.last_restart_unix = now
        self._emit(EVENT_RESTARTING, f"restarting: {reason}")
        if self._kill.wait(pol.delay_sec):
            return False
        return True

    # ---------------------------------------------------------------- kill

    def write_rendered_file(self, rel: str, content: str,
                            perms: str = "0644") -> str:
        """Write a rendered template/secret into the task dir. Also the
        re-render path of the template watcher (change_mode flow)."""
        path = os.path.join(self.task_dir, rel.lstrip("/"))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            mode = int(perms, 8)
        except (ValueError, TypeError):
            mode = 0o600
        # create with the final mode from the start: secrets must never
        # transit through a umask-default world-readable window
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        with os.fdopen(fd, "w") as f:
            f.write(content)
        os.chmod(path, mode)   # existing file: tighten to the ask
        return path

    def kill(self, reason: str = "") -> None:
        self._emit(EVENT_KILLING, reason or "task is being killed")
        self._kill.set()

    def signal(self, sig: str, reason: str = "") -> None:
        """Deliver a signal to the running task (ref taskrunner Signal /
        client/alloc_endpoint.go Allocations.Signal)."""
        if self.state.state != TASK_STATE_RUNNING:
            raise ValueError(f"task {self.task.name!r} is not running")
        self._emit(EVENT_SIGNALING, reason or f"signal {sig}")
        self.driver.signal_task(self.task_id, sig)

    def restart(self, reason: str = "") -> None:
        """Stop and rerun the task, bypassing restart-policy limits (ref
        taskrunner Restart / client/alloc_endpoint.go Allocations.Restart)."""
        if self.state.state != TASK_STATE_RUNNING:
            # pending (between runs) or dead: stop_task would be a no-op and
            # the flag would fire a spurious restart on the NEXT exit
            raise ValueError(f"task {self.task.name!r} is not running")
        self._emit(EVENT_RESTART_SIGNAL,
                   reason or "restart requested by user")
        self._restart_req = True
        self.driver.stop_task(self.task_id,
                              kill_timeout=self.task.kill_timeout_sec,
                              sig=self.task.kill_signal)

    def stats(self) -> dict:
        if self.state.state != TASK_STATE_RUNNING:
            return {"cpu_percent": 0.0, "memory_rss_bytes": 0}
        return self.driver.task_stats(self.task_id)

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def restore(self, handle: TaskHandle) -> bool:
        """Reattach to a live task after client restart (ref
        task_runner.go:1129 restoreHandle)."""
        if self.driver.recover_task(handle):
            self.handle = handle
            self._thread = threading.Thread(
                target=self._run_restored, daemon=True,
                name=f"task-{self.task.name}")
            self._thread.start()
            return True
        return False

    def _run_restored(self) -> None:
        self._set_state(TASK_STATE_RUNNING, EVENT_RECEIVED,
                        "task reattached after client restart")
        result = self._wait_for_exit()
        if self._kill.is_set():
            self._emit(EVENT_KILLED, "task killed")
            self._finish(failed=False)
            return
        failed = result is None or not result.successful()
        self._set_state(TASK_STATE_PENDING, EVENT_TERMINATED,
                        f"exit code: {result.exit_code if result else -1}")
        if self._should_restart(failed=failed, reason="post-restore exit"):
            self.run()
            return
        self._finish(failed=failed)

    # --------------------------------------------------------------- state

    def _emit(self, etype: str, message: str) -> None:
        self.state.events.append(TaskEvent(type=etype, time_unix=time.time(),
                                           message=message))
        self.on_state_change(self.task.name, self.state)

    def _set_state(self, state: str, etype: str, message: str) -> None:
        self.state.state = state
        if state == TASK_STATE_RUNNING:
            self.state.started_at = time.time()
        self.state.events.append(TaskEvent(type=etype, time_unix=time.time(),
                                           message=message))
        self.on_state_change(self.task.name, self.state)

    def _finish(self, failed: bool) -> None:
        self.state.state = TASK_STATE_DEAD
        self.state.failed = failed
        self.state.finished_at = time.time()
        if self._logmon is not None:
            self._logmon.stop()
        self.driver.destroy_task(self.task_id)
        self.on_state_change(self.task.name, self.state)
        self._done.set()

    def _fail(self, etype: str, message: str) -> None:
        self._emit(etype, message)
        self._finish(failed=True)
