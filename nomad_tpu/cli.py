"""CLI (ref command/ — the `nomad <cmd>` surface over the HTTP API).

Usage:
  python -m nomad_tpu.cli agent -dev [-port N]
  python -m nomad_tpu.cli job run <spec.json>
  python -m nomad_tpu.cli job status [job_id]
  python -m nomad_tpu.cli job stop [-purge] <job_id>
  python -m nomad_tpu.cli job dispatch <job_id> [-meta k=v ...]
  python -m nomad_tpu.cli node status [node_id]
  python -m nomad_tpu.cli node drain -enable <node_id>
  python -m nomad_tpu.cli node eligibility -enable|-disable <node_id>
  python -m nomad_tpu.cli alloc status <alloc_id>
  python -m nomad_tpu.cli eval status <eval_id>
  python -m nomad_tpu.cli deployment list|status|promote <...>
  python -m nomad_tpu.cli trace [eval_id] [-chrome out.json]
  python -m nomad_tpu.cli operator scheduler get-config
  python -m nomad_tpu.cli operator scheduler set-config -scheduler-algorithm <alg>
  python -m nomad_tpu.cli system gc
  python -m nomad_tpu.cli server members
  python -m nomad_tpu.cli status
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time
import urllib.error
import urllib.parse
import urllib.request


def _addr() -> str:
    return os.environ.get("NOMAD_ADDR", "http://127.0.0.1:4646")


def _die(msg: str) -> None:
    print(f"Error: {msg}", file=sys.stderr)
    sys.exit(1)


def api_raw(method: str, path: str) -> bytes:
    """Non-JSON endpoints (log/file contents)."""
    req = urllib.request.Request(_addr() + path, method=method)
    token = os.environ.get("NOMAD_TOKEN", "")
    if token:
        req.add_header("X-Nomad-Token", token)
    with urllib.request.urlopen(req, timeout=35) as resp:
        return resp.read()


def api(method: str, path: str, body=None):
    url = _addr() + path
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"}
    token = os.environ.get("NOMAD_TOKEN", "")
    if token:
        headers["X-Nomad-Token"] = token
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=35) as resp:
            return json.loads(resp.read() or "null")
    except urllib.error.HTTPError as e:
        try:
            err = json.loads(e.read()).get("error", str(e))
        except Exception:   # noqa: BLE001
            err = str(e)
        print(f"Error: {err}", file=sys.stderr)
        sys.exit(1)
    except urllib.error.URLError as e:
        print(f"Error connecting to {url}: {e.reason}", file=sys.stderr)
        sys.exit(1)


def _table(rows: list[list], headers: list[str]) -> None:
    widths = [max(len(str(r[i])) for r in [headers] + rows)
              for i in range(len(headers))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*headers))
    for r in rows:
        print(fmt.format(*[str(c) for c in r]))


# ------------------------------------------------------------------ agent

def cmd_agent(args) -> None:
    from .agent import Agent, AgentConfig
    cfg = AgentConfig(dev_mode=args.dev)
    # config files load first (ref agent.go: files merge in order)...
    config_paths = list(getattr(args, "config", []) or [])
    if config_paths:
        from .agent.config_file import (
            ConfigError, apply_to_agent_config, load_config,
        )
        try:
            apply_to_agent_config(cfg, load_config(config_paths))
        except (ConfigError, OSError) as e:
            _die(str(e))
    # ...then explicitly passed CLI flags override file values. Agent
    # flags default to None (sentinel), so ANY value the operator typed
    # wins — including typing a flag's documented default back — and
    # AgentConfig's own defaults apply when neither source sets a field.
    fields = {"port": "http_port", "data_dir": "data_dir",
              "workers": "num_workers", "acl_enabled": "acl_enabled",
              "region": "region",
              "authoritative_region": "authoritative_region",
              "rpc_port": "rpc_port", "gossip_port": "gossip_port",
              "bootstrap_expect": "bootstrap_expect",
              "replication_token": "replication_token",
              "plugin_dir": "plugin_dir"}
    for arg_name, cfg_field in fields.items():
        val = getattr(args, arg_name, None)
        if val is not None:
            setattr(cfg, cfg_field, val)
    if getattr(args, "join", None):
        cfg.join = tuple(args.join)
    agent = Agent(cfg, logger=lambda m: print(f"    {m}", flush=True))
    agent.start()
    mode = []
    if agent.server:
        mode.append("server")
    if agent.client:
        mode.append("client")
    print("==> nomad_tpu agent started! Log data will stream below:")
    print(f"    Mode: {' + '.join(mode)}{' (dev)' if args.dev else ''}")
    print(f"    HTTP: {agent.http_addr}")
    if agent.client:
        print(f"    Node: {agent.client.node.name} ({agent.client.node.id[:8]})")
    stop = False

    def on_sig(*_):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGINT, on_sig)
    signal.signal(signal.SIGTERM, on_sig)
    while not stop:
        time.sleep(0.2)
    print("==> caught signal, shutting down")
    agent.shutdown()


# ------------------------------------------------------------------- jobs

def _load_spec(path: str, var_flags=None) -> dict:
    """Load a jobspec file — HCL (.nomad/.hcl) or JSON — into the API job
    payload (ref command/job_run.go: HCL parse then api.Job submit)."""
    if path.endswith(".json"):
        with open(path) as f:
            spec = json.load(f)
        return spec if "Job" in spec else {"Job": spec}
    from .jobspec import parse_file
    from .api_codec import to_api
    variables = {}
    for kv in var_flags or []:
        k, _, v = kv.partition("=")
        variables[k] = v
    job = parse_file(path, variables)
    return {"Job": to_api(job)}


def cmd_job_run(args) -> None:
    spec = _load_spec(args.spec, getattr(args, "var", None))
    resp = api("PUT", "/v1/jobs", spec)
    print(f"==> Evaluation {resp.get('eval_id', '')[:8]} created")
    if args.detach:
        return
    eval_id = resp.get("eval_id")
    if not eval_id:
        return
    for _ in range(100):
        ev = api("GET", f"/v1/evaluation/{eval_id}")
        if ev["Status"] in ("complete", "failed", "canceled"):
            print(f"==> Evaluation status: {ev['Status']}")
            if ev.get("FailedTGAllocs"):
                for tg, m in ev["FailedTGAllocs"].items():
                    print(f"    group {tg!r}: placement failed "
                          f"(filtered {m.get('NodesFiltered', 0)}, "
                          f"exhausted {m.get('NodesExhausted', 0)})")
            blocked = ev.get("BlockedEval")
            if blocked:
                print(f"    blocked eval {blocked[:8]} waiting for capacity")
            return
        time.sleep(0.2)


def cmd_job_status(args) -> None:
    if not args.job_id:
        jobs = api("GET", "/v1/jobs")
        if not jobs:
            print("No running jobs")
            return
        _table([[j["ID"], j["Type"], j["Priority"], j["Status"]]
                for j in jobs], ["ID", "Type", "Priority", "Status"])
        return
    job = api("GET", f"/v1/job/{args.job_id}")
    print(f"ID            = {job['ID']}")
    print(f"Name          = {job['Name']}")
    print(f"Type          = {job['Type']}")
    print(f"Priority      = {job['Priority']}")
    print(f"Status        = {job['Status']}")
    print(f"Version       = {job['Version']}")
    allocs = api("GET", f"/v1/job/{args.job_id}/allocations")
    if allocs:
        print("\nAllocations")
        _table([[a["ID"][:8], a["NodeName"] or a["NodeID"][:8], a["TaskGroup"],
                 a["JobVersion"], a["DesiredStatus"], a["ClientStatus"]]
                for a in allocs],
               ["ID", "Node", "Group", "Version", "Desired", "Status"])


def cmd_job_stop(args) -> None:
    path = f"/v1/job/{args.job_id}"
    if args.purge:
        path += "?purge=true"
    resp = api("DELETE", path)
    print(f"==> Evaluation {resp.get('eval_id', '')[:8]} created")


def _ann_suffix(d: dict) -> str:
    """Scheduling-consequence suffix (ref command/job_plan.go: the
    "(forces create)" renderings of scheduler/annotate.go output)."""
    ann = d.get("Annotations") or []
    return f" ({', '.join(ann)})" if ann else ""


def _render_field_diffs(fields: list, indent: str,
                        verbose: bool = False) -> None:
    marks = {"Added": "+", "Deleted": "-", "Edited": "+/-", "None": " "}
    for f in fields or []:
        m = marks.get(f["Type"], " ")
        sfx = _ann_suffix(f)
        if f["Type"] == "Edited":
            print(f"{indent}{m} {f['Name']}: "
                  f"{f['Old']!r} => {f['New']!r}{sfx}")
        elif f["Type"] == "Added":
            print(f"{indent}{m} {f['Name']}: {f['New']!r}{sfx}")
        elif f["Type"] == "Deleted":
            print(f"{indent}{m} {f['Name']}: {f['Old']!r}{sfx}")
        elif verbose:   # Type None: context, shown only under -verbose
            print(f"{indent}{m} {f['Name']}: {f['New']!r}{sfx}")


def _render_object_diffs(objs: list, indent: str,
                         verbose: bool = False) -> None:
    for o in objs or []:
        if o["Type"] == "None" and not verbose:
            continue
        print(f"{indent}{o['Type']} {o['Name']} {{")
        _render_field_diffs(o.get("Fields"), indent + "  ", verbose)
        _render_object_diffs(o.get("Objects"), indent + "  ", verbose)
        print(f"{indent}}}")


def cmd_job_plan(args) -> None:
    spec = _load_spec(args.spec, getattr(args, "var", None))
    spec["Diff"] = True
    verbose = bool(getattr(args, "verbose", False))
    resp = api("PUT", f"/v1/job/{spec['Job'].get('Id') or spec['Job'].get('ID')}/plan",
               spec)
    diff = resp.get("Diff") or {}
    if diff.get("Type", "None") != "None":
        print(f"{diff['Type']} job {diff.get('ID', '')!r}")
        _render_field_diffs(diff.get("Fields"), "  ", verbose)
        _render_object_diffs(diff.get("Objects"), "  ", verbose)
        for tg in diff.get("TaskGroups", []):
            if tg["Type"] == "None" and not verbose:
                continue
            print(f"  {tg['Type']} group {tg['Name']!r}")
            _render_field_diffs(tg.get("Fields"), "    ", verbose)
            _render_object_diffs(tg.get("Objects"), "    ", verbose)
            for t in tg.get("Tasks", []):
                if t["Type"] == "None" and not verbose:
                    continue
                print(f"    {t['Type']} task {t['Name']!r}"
                      f"{_ann_suffix(t)}")
                _render_field_diffs(t.get("Fields"), "      ", verbose)
                _render_object_diffs(t.get("Objects"), "      ", verbose)
    else:
        print("No changes")
    ann = resp.get("Annotations") or {}
    for tg, upd in (ann.get("DesiredTgUpdates") or {}).items():
        parts = [f"{k.lower()} {v}" for k, v in sorted(upd.items()) if v]
        if parts:
            print(f"==> group {tg!r}: " + ", ".join(parts))
    failed = resp.get("FailedTGAllocs")
    if failed:
        for tg, m in failed.items():
            print(f"!!  group {tg!r} would fail to place "
                  f"(filtered {m.get('NodesFiltered', 0)}, "
                  f"exhausted {m.get('NodesExhausted', 0)})")
    print(f"Job Modify Index: {resp.get('JobModifyIndex', 0)}")


def cmd_job_validate(args) -> None:
    try:
        _load_spec(args.spec, getattr(args, "var", None))
    except Exception as e:   # noqa: BLE001
        print(f"Job validation errors:\n  {e}")
        raise SystemExit(1)
    print("Job validation successful")


def cmd_job_inspect(args) -> None:
    job = api("GET", f"/v1/job/{args.job_id}")
    print(json.dumps({"Job": job}, indent=2, default=str))


def cmd_job_dispatch(args) -> None:
    meta = dict(kv.split("=", 1) for kv in (args.meta or []))
    resp = api("PUT", f"/v1/job/{args.job_id}/dispatch", {"Meta": meta})
    print(f"==> Dispatched job {resp['dispatched_job_id']}")


def cmd_job_scale(args) -> None:
    """ref command/job_scale.go"""
    resp = api("PUT", f"/v1/job/{args.job_id}/scale", {
        "Target": {"Group": args.group}, "Count": int(args.count),
        "Message": "scaled via CLI"})
    print(f"==> Evaluation {resp.get('eval_id', '')[:8]} created")


def cmd_job_revert(args) -> None:
    """ref command/job_revert.go"""
    resp = api("PUT", f"/v1/job/{args.job_id}/revert",
               {"JobVersion": int(args.version)})
    print(f"==> Evaluation {resp.get('eval_id', '')[:8]} created")


def cmd_job_history(args) -> None:
    """ref command/job_history.go"""
    versions = api("GET", f"/v1/job/{args.job_id}/versions")
    _table([[str(v["Version"]), "true" if v.get("Stable") else "false",
             v["Status"]] for v in versions],
           ["Version", "Stable", "Status"])


def cmd_job_eval(args) -> None:
    """ref command/job_eval.go: force a fresh evaluation of the job."""
    resp = api("PUT", f"/v1/job/{args.job_id}/evaluate",
               {"EvalOptions":
                {"ForceReschedule": bool(args.force_reschedule)}})
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")


def cmd_job_periodic_force(args) -> None:
    """ref command/job_periodic_force.go"""
    resp = api("PUT", f"/v1/job/{args.job_id}/periodic/force", {})
    print(f"==> Dispatched periodic child {resp['dispatched_job_id']}")


def cmd_job_deployments(args) -> None:
    """ref command/job_deployments.go"""
    ds = api("GET", f"/v1/job/{args.job_id}/deployments")
    _table([[d["ID"][:8], d["JobVersion"], d["Status"],
             d["StatusDescription"]] for d in ds],
           ["ID", "Version", "Status", "Description"])


# ---------------------------------------------------------------- volumes

def cmd_volume_status(args) -> None:
    """ref command/volume_status.go"""
    if not args.volume_id:
        vols = api("GET", "/v1/volumes")
        _table([[v["ID"], v["Name"], v["PluginID"],
                 "true" if v["Schedulable"] else "false",
                 v["AccessMode"]] for v in vols],
               ["ID", "Name", "Plugin", "Schedulable", "Access"])
        return
    v = api("GET", f"/v1/volume/csi/{args.volume_id}")
    print(f"ID          = {v['ID']}")
    print(f"Name        = {v['Name']}")
    print(f"Plugin      = {v['PluginID']}")
    print(f"Schedulable = {v['Schedulable']}")
    print(f"Access Mode = {v['AccessMode']}")
    print(f"Readers     = {len(v.get('ReadClaims') or {})}")
    print(f"Writers     = {len(v.get('WriteClaims') or {})}")


def cmd_volume_register(args) -> None:
    with open(args.spec) as f:
        spec = json.load(f)
    vol = spec.get("Volume", spec)
    api("PUT", f"/v1/volume/csi/{vol.get('ID', '')}", {"Volume": vol})
    print(f"==> Registered volume {vol.get('ID')}")


def cmd_volume_deregister(args) -> None:
    force = "?force=true" if args.force else ""
    api("DELETE", f"/v1/volume/csi/{args.volume_id}{force}")
    print(f"==> Deregistered volume {args.volume_id}")


def cmd_volume_detach(args) -> None:
    """ref command/volume_detach.go"""
    out = api("DELETE",
              f"/v1/volume/csi/{args.volume_id}/detach?node={args.node_id}")
    print(f"==> Released {out.get('NumReleased', 0)} claim(s) on "
          f"{args.volume_id}")


def cmd_plugin_status(args) -> None:
    """ref command/plugin_status.go"""
    if not args.plugin_id:
        plugins = api("GET", "/v1/plugins")
        _table([[p["ID"], p["Provider"],
                 f"{p['ControllersHealthy']}/{p['ControllersExpected']}",
                 f"{p['NodesHealthy']}/{p['NodesExpected']}"]
                for p in plugins],
               ["ID", "Provider", "Controllers", "Nodes"])
        return
    p = api("GET", f"/v1/plugin/csi/{args.plugin_id}")
    print(f"ID       = {p['ID']}")
    print(f"Provider = {p['Provider']}")
    print(f"Version  = {p['Version']}")


# ------------------------------------------------------------------ nodes

def cmd_node_status(args) -> None:
    if not args.node_id:
        nodes = api("GET", "/v1/nodes")
        _table([[n["ID"][:8], n["Name"], n["Datacenter"], n["Status"],
                 n["SchedulingEligibility"], "true" if n["Drain"] else "false"]
                for n in nodes],
               ["ID", "Name", "DC", "Status", "Eligibility", "Drain"])
        return
    node = api("GET", f"/v1/node/{args.node_id}")
    print(f"ID          = {node['ID']}")
    print(f"Name        = {node['Name']}")
    print(f"Status      = {node['Status']}")
    print(f"Eligibility = {node['SchedulingEligibility']}")
    allocs = api("GET", f"/v1/node/{args.node_id}/allocations")
    if allocs:
        print("\nAllocations")
        _table([[a["ID"][:8], a["JobID"], a["TaskGroup"], a["DesiredStatus"],
                 a["ClientStatus"]] for a in allocs],
               ["ID", "Job", "Group", "Desired", "Status"])


def cmd_node_drain(args) -> None:
    body = {}
    if args.enable:
        body["DrainSpec"] = {"Deadline": args.deadline,
                             "IgnoreSystemJobs": args.ignore_system}
    else:
        body["DrainSpec"] = None
        body["MarkEligible"] = True
    api("PUT", f"/v1/node/{args.node_id}/drain", body)
    print(f"==> Node {args.node_id[:8]} drain "
          f"{'enabled' if args.enable else 'disabled'}")
    if args.enable and getattr(args, "monitor", False):
        # ref command/node_drain.go -monitor: poll until every non-system
        # alloc on the node reaches a terminal or replaced state
        seen = set()
        while True:
            node = api("GET", f"/v1/node/{args.node_id}")
            allocs = api("GET", f"/v1/node/{args.node_id}/allocations")
            remaining = [a for a in allocs
                         if a["DesiredStatus"] == "run"
                         and a["ClientStatus"] in ("pending", "running")]
            for a in allocs:
                key = (a["ID"], a["DesiredStatus"], a["ClientStatus"])
                if key not in seen and a["DesiredStatus"] != "run":
                    seen.add(key)
                    print(f"    alloc {a['ID'][:8]} ({a['JobID']}) -> "
                          f"{a['DesiredStatus']}/{a['ClientStatus']}")
            if not node.get("DrainStrategy"):
                # drain strategy removed: done — system-job allocs may
                # legitimately keep running (-ignore-system), so don't
                # wait on `remaining` once the drainer has finished
                # (ref node_drain.go monitor exits on drain completion)
                print("==> Drain complete" if not remaining else
                      f"==> Drain complete ({len(remaining)} alloc(s) "
                      "left running)")
                return
            if not remaining:
                print("==> All allocations drained "
                      "(node still marked draining)")
                return
            time.sleep(1.0)


def cmd_node_eligibility(args) -> None:
    elig = "eligible" if args.enable else "ineligible"
    api("PUT", f"/v1/node/{args.node_id}/eligibility", {"Eligibility": elig})
    print(f"==> Node {args.node_id[:8]} marked {elig}")


# ------------------------------------------------------------------ other

def cmd_alloc_signal(args) -> None:
    """ref command/alloc_signal.go"""
    alloc_id, task = _alloc_task(args.alloc_id, args.task)
    api("POST", f"/v1/client/allocation/{alloc_id}/signal",
        {"Task": task, "Signal": args.signal})
    print(f"Signalled {args.signal} to task {task!r} of {alloc_id[:8]}")


def cmd_alloc_restart(args) -> None:
    """ref command/alloc_restart.go"""
    alloc_id, task = _alloc_task(args.alloc_id, args.task)
    api("POST", f"/v1/client/allocation/{alloc_id}/restart",
        {"Task": task})
    print(f"Restarted task {task!r} of {alloc_id[:8]}")


def cmd_alloc_stop(args) -> None:
    """ref command/alloc_stop.go"""
    alloc_id, _ = _alloc_task(args.alloc_id, "-")
    out = api("POST", f"/v1/allocation/{alloc_id}/stop", {})
    ev = out.get("eval_id") or out.get("EvalID") or ""
    print(f"Stopped {alloc_id[:8]} (eval {ev[:8]})")


def cmd_alloc_fs(args) -> None:
    """ref command/alloc_fs.go: ls/stat/cat inside the alloc dir"""
    alloc_id, _ = _alloc_task(args.alloc_id, "-")
    path = urllib.parse.quote(args.path or "/")
    st = api("GET", f"/v1/client/fs/stat/{alloc_id}?path={path}")
    if args.stat:
        print(json.dumps(st, indent=2))
        return
    if st.get("IsDir"):
        listing = api("GET", f"/v1/client/fs/ls/{alloc_id}?path={path}")
        _table([[e["Name"], "dir" if e["IsDir"] else e["Size"],
                 e["FileMode"]] for e in listing],
               ["Name", "Size", "Mode"])
    else:
        sys.stdout.buffer.write(api_raw(
            "GET", f"/v1/client/fs/cat/{alloc_id}?path={path}"))


def cmd_eval_list(args) -> None:
    """ref command/eval_list.go"""
    evs = api("GET", "/v1/evaluations")
    _table([[e["ID"][:8], e["JobID"], e["Type"], e["TriggeredBy"],
             e["Status"]] for e in evs[:args.limit]],
           ["ID", "Job", "Type", "Triggered By", "Status"])


def cmd_server_force_leave(args) -> None:
    """ref command/server_force_leave.go"""
    api("POST", "/v1/agent/force-leave?node="
        + urllib.parse.quote(args.name))
    print(f"Force-left {args.name}")


def cmd_alloc_status(args) -> None:
    a = api("GET", f"/v1/allocation/{args.alloc_id}")
    print(f"ID            = {a['ID']}")
    print(f"Name          = {a['Name']}")
    print(f"Node          = {a['NodeName'] or a['NodeID'][:8]}")
    print(f"Job           = {a['JobID']}")
    print(f"Desired       = {a['DesiredStatus']}")
    print(f"Status        = {a['ClientStatus']}")
    for task, st in (a.get("TaskStates") or {}).items():
        print(f"\nTask {task!r} is {st['State']}"
              f"{' (failed)' if st['Failed'] else ''}")
        for ev in st.get("Events", [])[-5:]:
            print(f"  {ev['Type']}: {ev['Message']}")


def _alloc_task(alloc_id: str, task: str) -> tuple[str, str]:
    """Resolve (full alloc id, task name) from a possibly-short id."""
    if len(alloc_id) == 36:
        a = api("GET", f"/v1/allocation/{alloc_id}")
    else:
        matches = [x for x in (api("GET", "/v1/allocations") or [])
                   if x["ID"].startswith(alloc_id)]
        if len(matches) != 1:
            _die(f"allocation {alloc_id!r} matched "
                 f"{len(matches)} allocations")
        a = api("GET", f"/v1/allocation/{matches[0]['ID']}")
    if not task:
        states = a.get("TaskStates") or {}
        if len(states) == 1:
            task = next(iter(states))
        else:
            _die(f"-task required (tasks: {', '.join(states) or '?'})")
    return a["ID"], task


def cmd_alloc_exec(args) -> None:
    """Interactive exec into a running task (ref command/alloc_exec.go):
    round-trips stdin/stdout through the session API until exit."""
    import base64
    import select
    # argparse REMAINDER swallows flags placed after the alloc id
    # (`alloc exec ID -task t -- cmd`); strip them out here
    rest = list(args.command)
    while rest and rest[0].startswith("-") and rest[0] != "--":
        flag = rest.pop(0)
        if flag == "-task" and rest:
            args.task = rest.pop(0)
        elif flag == "-tty":
            args.tty = True
    if rest and rest[0] == "--":        # only the SEPARATOR is stripped:
        rest = rest[1:]                 # later '--' belong to the command
    command = rest
    if not command:
        _die("command required, e.g.: alloc exec <id> -task web -- /bin/sh")
    alloc_id, task = _alloc_task(args.alloc_id, args.task)
    out = api("POST", f"/v1/client/allocation/{alloc_id}/exec",
              {"Task": task, "Cmd": command, "Tty": args.tty})
    sid = out["SessionID"]
    stdin_open = True
    try:
        while True:
            # pump any ready local stdin to the remote session
            if stdin_open and select.select([sys.stdin], [], [], 0)[0]:
                line = sys.stdin.buffer.readline()
                if line:
                    api("POST", f"/v1/client/exec-session/{sid}",
                        {"Stdin": base64.b64encode(line).decode()})
                else:                    # local EOF -> remote EOF, once
                    api("POST", f"/v1/client/exec-session/{sid}",
                        {"StdinEOF": True})
                    stdin_open = False
            chunk = api("GET", f"/v1/client/exec-session/{sid}?wait=0.5")
            data = base64.b64decode(chunk.get("Stdout", ""))
            err = base64.b64decode(chunk.get("Stderr", ""))
            if data:
                sys.stdout.buffer.write(data)
                sys.stdout.flush()
            if err:
                sys.stderr.buffer.write(err)
                sys.stderr.flush()
            if chunk.get("Exited") and not data and not err:
                code = chunk.get("ExitCode") or 0
                sys.exit(code)
    finally:
        try:
            api("DELETE", f"/v1/client/exec-session/{sid}")
        except Exception:               # noqa: BLE001
            pass


def cmd_alloc_logs(args) -> None:
    """ref command/alloc_logs.go (-f follows)"""
    import base64
    alloc_id, task = _alloc_task(args.alloc_id, args.task)
    log_type = "stderr" if args.stderr else "stdout"
    if not args.follow:
        data = api_raw("GET", f"/v1/client/fs/logs/{alloc_id}?task={task}"
                       f"&type={log_type}")
        sys.stdout.buffer.write(data)
        return
    offset = 0
    try:
        while True:
            out = api("GET", f"/v1/client/fs/logs/{alloc_id}?task={task}"
                      f"&type={log_type}&follow=true&offset={offset}&wait=5")
            data = base64.b64decode(out.get("Data", ""))
            offset = int(out.get("Offset", offset))
            if data:
                sys.stdout.buffer.write(data)
                sys.stdout.flush()
    except (BrokenPipeError, KeyboardInterrupt):
        sys.exit(0)                     # downstream pipe closed / ^C


def cmd_eval_status(args) -> None:
    """ref command/eval_status.go: summary + per-group placement
    failure metrics + related allocations."""
    ev = api("GET", f"/v1/evaluation/{args.eval_id}")
    for k in ("ID", "Type", "TriggeredBy", "JobID", "Priority", "Status",
              "StatusDescription"):
        print(f"{k:<18}= {ev.get(k)}")
    if ev.get("WaitUntilUnix"):
        print(f"{'WaitUntil':<18}= {ev['WaitUntilUnix']}")
    if ev.get("BlockedEval"):
        # full id: eval lookups are exact-match, a truncated id can't be
        # fed back into `eval status`
        print(f"{'BlockedEval':<18}= {ev['BlockedEval']}")
    failed = ev.get("FailedTGAllocs") or {}
    for tg, m in failed.items():
        print(f"\nTask Group {tg!r} (failed to place):")
        print(f"  * Nodes evaluated: {m.get('NodesEvaluated', 0)}, "
              f"filtered: {m.get('NodesFiltered', 0)}, "
              f"exhausted: {m.get('NodesExhausted', 0)}")
        for reason, n in (m.get("ConstraintFiltered") or {}).items():
            print(f"  * Constraint {reason!r} filtered {n} node(s)")
        for dim, n in (m.get("DimensionExhausted") or {}).items():
            print(f"  * Resources exhausted on {n} node(s): {dim}")
        for klass, n in (m.get("ClassExhausted") or {}).items():
            print(f"  * Class {klass!r} exhausted on {n} node(s)")
        # tensor-path explain (ISSUE 11): winning-row score metadata the
        # device solve attached — who DID win, next to why others lost
        for sm in (m.get("ScoreMeta") or [])[:5]:
            nid = (sm.get("node_id") or sm.get("NodeID") or "")[:8]
            score = sm.get("normalized_score",
                           sm.get("NormalizedScore", 0.0))
            print(f"  * Scored node {nid}: {score:.4f}")
    allocs = api("GET", f"/v1/evaluation/{args.eval_id}/allocations")
    if allocs:
        print("\nAllocations")
        _table([[a["ID"][:8], a["TaskGroup"],
                 a["NodeName"] or a["NodeID"][:8],
                 a["DesiredStatus"], a["ClientStatus"]] for a in allocs],
               ["ID", "Group", "Node", "Desired", "Status"])


def cmd_trace(args) -> None:
    """Eval-trace browsing (ISSUE 7): `trace` lists retained traces,
    `trace <eval-id>` renders a text waterfall of the span tree plus the
    shared fan-in spans (micro-batch dispatch, coalesced commit) the
    eval rode; `-chrome FILE` saves Chrome trace-event JSON for
    chrome://tracing / Perfetto."""
    if not args.ref:
        out = api("GET", f"/v1/traces?limit={args.limit}")
        trs = out.get("Traces", [])
        if not trs:
            print("No traces retained (telemetry_trace_enabled off, "
                  "sampled out, or nothing ran yet)")
            return
        _table([[t["trace_id"][:12], (t["eval_id"] or "-")[:8],
                 t["name"], t["status"],
                 f"{t['duration_s'] * 1000:.1f}ms", t["spans"]]
                for t in trs],
               ["Trace", "Eval", "Name", "Status", "Duration", "Spans"])
        st = out.get("Stats", {})
        print(f"\n{st.get('retained', 0)} retained / "
              f"{st.get('started', 0)} started, "
              f"sample_rate={st.get('sample_rate')}")
        return
    ref = urllib.parse.quote(args.ref)
    if args.chrome:
        raw = api_raw("GET", f"/v1/traces/{ref}?format=chrome")
        with open(args.chrome, "wb") as f:
            f.write(raw)
        print(f"Wrote Chrome trace-event JSON to {args.chrome} "
              f"(open in chrome://tracing or ui.perfetto.dev)")
        return
    tr = api("GET", f"/v1/traces/{ref}")
    dur = max(tr.get("duration_s") or 0.0, 1e-9)
    print(f"Trace   {tr['trace_id']}  ({tr['name']}, "
          f"status={tr['status']})")
    if tr.get("eval_id"):
        print(f"Eval    {tr['eval_id']}")
    print(f"Wall    {dur * 1000:.2f}ms\n")
    spans = list(tr.get("spans", ()))
    by_parent: dict[str, list] = {}
    for sp in spans:
        by_parent.setdefault(sp["parent"], []).append(sp)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s["ts"])
    width = 36
    t0 = tr["start_unix"]

    def bar(sp) -> str:
        off = max(0.0, sp["ts"] - t0) / dur
        frac = min(1.0, sp["dur"] / dur)
        lead = min(width - 1, int(off * width))
        fill = max(1, int(frac * width))
        fill = min(fill, width - lead)
        return " " * lead + "█" * fill + " " * (width - lead - fill)

    def attrs_str(sp) -> str:
        keep = {k: v for k, v in (sp.get("attrs") or {}).items()
                if k in ("tier", "kernel", "cache", "lanes", "plans",
                         "demotions", "raft_index", "error", "noop",
                         "index")}
        mark = "" if sp["status"] == "ok" else f" !{sp['status']}"
        link = " ~fanin" if sp.get("links") else ""
        return mark + link + (f"  {keep}" if keep else "")

    def walk(sp, depth: int) -> None:
        name = ("  " * depth + sp["name"])[:30]
        print(f"{name:<30} |{bar(sp)}| {sp['dur'] * 1000:9.3f}ms"
              f"{attrs_str(sp)}")
        for kid in by_parent.get(sp["id"], ()):
            walk(kid, depth + 1)

    roots = by_parent.get("", [])
    orphans = [sp for sp in spans
               if sp["parent"] and not any(
                   p["id"] == sp["parent"] for p in spans)]
    for sp in roots + sorted(orphans, key=lambda s: s["ts"]):
        walk(sp, 0)
    linked = tr.get("linked_spans", ())
    if linked:
        print("\nShared fan-in spans this eval rode:")
        for sp in sorted(linked, key=lambda s: s["ts"]):
            print(f"~ {sp['name']:<28} |{bar(sp)}| "
                  f"{sp['dur'] * 1000:9.3f}ms{attrs_str(sp)}")


def cmd_deployment(args) -> None:
    if args.action == "list":
        ds = api("GET", "/v1/deployments")
        _table([[d["ID"][:8], d["JobID"], d["JobVersion"], d["Status"],
                 d["StatusDescription"]] for d in ds],
               ["ID", "Job", "Version", "Status", "Description"])
    elif args.action == "status":
        d = api("GET", f"/v1/deployment/{args.id}")
        print(json.dumps(d, indent=2))
    elif args.action == "promote":
        api("PUT", f"/v1/deployment/promote/{args.id}", {})
        print("==> Deployment promoted")
    elif args.action == "fail":
        api("PUT", f"/v1/deployment/fail/{args.id}", {})
        print("==> Deployment marked failed")
    elif args.action == "pause":
        api("PUT", f"/v1/deployment/pause/{args.id}", {"Pause": True})
        print("==> Deployment paused")
    elif args.action == "resume":
        api("PUT", f"/v1/deployment/pause/{args.id}", {"Pause": False})
        print("==> Deployment resumed")


def cmd_operator_scheduler(args) -> None:
    if args.action == "get-config":
        cfg = api("GET", "/v1/operator/scheduler/configuration")
        print(json.dumps(cfg, indent=2))
    else:
        cfg = api("GET", "/v1/operator/scheduler/configuration")[
            "SchedulerConfig"]
        if args.scheduler_algorithm:
            cfg["SchedulerAlgorithm"] = args.scheduler_algorithm
        if args.memory_oversubscription is not None:
            cfg["MemoryOversubscriptionEnabled"] = \
                args.memory_oversubscription == "true"
        api("PUT", "/v1/operator/scheduler/configuration", cfg)
        print("==> Scheduler configuration updated")


def cmd_operator_raft(args) -> None:
    """ref command/operator_raft_list.go / operator_raft_remove.go"""
    if args.action == "list-peers":
        cfg = api("GET", "/v1/operator/raft/configuration")
        _table([[sv["ID"], sv["Address"],
                 "leader" if sv["Leader"] else "follower",
                 "true" if sv["Voter"] else "false"]
                for sv in cfg["Servers"]],
               ["ID", "Address", "State", "Voter"])
    else:
        q = []
        if args.peer_id:
            q.append(f"id={args.peer_id}")
        if args.peer_address:
            q.append(f"address={args.peer_address}")
        api("DELETE", "/v1/operator/raft/peer?" + "&".join(q))
        print("==> Peer removed")


def cmd_operator_snapshot(args) -> None:
    """ref command/operator_snapshot_save.go / _restore.go"""
    if args.action == "inspect":
        return cmd_operator_snapshot_inspect(args)
    from .api import Client
    sdk = Client(timeout=60)
    if args.action == "save":
        data = sdk.operator.snapshot_save()
        with open(args.file, "wb") as f:
            f.write(data)
        print(f"==> Snapshot saved to {args.file} ({len(data)} bytes)")
    else:
        with open(args.file, "rb") as f:
            data = f.read()
        sdk.operator.snapshot_restore(data)
        print("==> Snapshot restored")


def cmd_operator_snapshot_inspect(args) -> None:
    """Offline snapshot summary — no server needed (ref
    helper/raftutil + command/operator_snapshot_inspect.go). Decodes
    through the restricted unpickler: snapshots are often handed around
    in support bundles, and a crafted pickle must not execute code."""
    from .rpc.codec import FrameError, decode
    with open(args.file, "rb") as f:
        try:
            blob = decode(f.read())
        except FrameError as e:
            _die(f"not a nomad-tpu snapshot: {e}")
    rows = []
    for table in ("nodes", "jobs", "job_versions", "job_summaries",
                  "evals", "allocs", "deployments", "periodic_launches",
                  "namespaces", "acl_policies", "acl_tokens",
                  "csi_volumes", "csi_plugins", "scaling_policies",
                  "services"):
        v = blob.get(table)
        if v is not None:
            rows.append([table, len(v)])
    print(f"Index         = {blob.get('index', 0)}")
    sc = blob.get("scheduler_config")
    if sc is not None:
        print(f"SchedulerAlg  = "
              f"{getattr(sc, 'scheduler_algorithm', '')}")
    print()
    _table(rows, ["Table", "Count"])


def cmd_operator_autopilot(args) -> None:
    if args.action == "get-config":
        print(json.dumps(api("GET", "/v1/operator/autopilot/configuration"),
                         indent=2))
    elif args.action == "health":
        print(json.dumps(api("GET", "/v1/operator/autopilot/health"),
                         indent=2))
    else:
        cfg = {}
        if args.cleanup_dead_servers is not None:
            cfg["CleanupDeadServers"] = args.cleanup_dead_servers == "true"
        api("PUT", "/v1/operator/autopilot/configuration", cfg)
        print("==> Autopilot configuration updated")


def cmd_operator_debug(args) -> None:
    """Capture a debug bundle (ref command/operator_debug.go): cluster
    state + agent internals + metrics sampled over a duration, written as
    nomad-debug-<ts>.tar.gz for support handoff."""
    import tarfile
    import tempfile
    import time as _time

    duration = float(args.duration)
    interval = max(float(args.interval), 0.25)
    captures = {
        "agent-self.json": ("GET", "/v1/agent/self"),
        "members.json": ("GET", "/v1/agent/members"),
        "nodes.json": ("GET", "/v1/nodes"),
        "jobs.json": ("GET", "/v1/jobs"),
        "allocations.json": ("GET", "/v1/allocations"),
        "evaluations.json": ("GET", "/v1/evaluations"),
        "deployments.json": ("GET", "/v1/deployments"),
        "scheduler-configuration.json":
            ("GET", "/v1/operator/scheduler/configuration"),
        # the server-side one-shot bundle (ISSUE 11): metrics + recent
        # traces + pressure/broker/state-cache/breaker stats + recent
        # placement-explain records + device-runtime telemetry
        "operator-debug.json": ("GET", "/v1/operator/debug"),
        "autopilot-health.json": ("GET", "/v1/operator/autopilot/health"),
        "raft-configuration.json":
            ("GET", "/v1/operator/raft/configuration"),
        "regions.json": ("GET", "/v1/regions"),
        "status-leader.json": ("GET", "/v1/status/leader"),
        "status-peers.json": ("GET", "/v1/status/peers"),
    }
    raw_captures = {
        "pprof-goroutine.txt": "/v1/agent/pprof/goroutine",
        "metrics.prom": "/v1/metrics?format=prometheus",
    }
    tmp = tempfile.mkdtemp(prefix="nomad-debug-")
    manifest = {"CapturedAt": _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             _time.gmtime()),
                "Duration": duration, "Interval": interval,
                "Files": [], "Errors": {}}

    def _save(name: str, payload) -> None:
        path = os.path.join(tmp, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            if isinstance(payload, str):
                f.write(payload)
            else:
                json.dump(payload, f, indent=2, default=str)
        manifest["Files"].append(name)

    # api_raw() (not api()): the JSON helper sys.exit(1)s on HTTP errors,
    # which would abort the whole bundle — a debug capture must record
    # the failure in the manifest and keep going
    for name, (method, path) in captures.items():
        try:
            _save(name, json.loads(api_raw(method, path) or b"null"))
        except Exception as e:  # noqa: BLE001 — capture what we can
            manifest["Errors"][name] = str(e)
    for name, path in raw_captures.items():
        try:
            _save(name, api_raw("GET", path).decode(errors="replace"))
        except Exception as e:  # noqa: BLE001
            manifest["Errors"][name] = str(e)
    # sampled captures: metrics at each interval tick over the duration
    # (ref operator_debug.go collectPeriodic)
    deadline = _time.time() + duration
    tick = 0
    while True:
        try:
            _save(f"metrics/metrics-{tick:03d}.json",
                  json.loads(api_raw("GET", "/v1/metrics") or b"null"))
        except Exception as e:  # noqa: BLE001
            manifest["Errors"][f"metrics-{tick}"] = str(e)
        tick += 1
        if _time.time() + interval > deadline:
            break
        _time.sleep(interval)
    _save("index.json", manifest)

    stamp = _time.strftime("%Y%m%d-%H%M%S")
    out = args.output or f"nomad-debug-{stamp}.tar.gz"
    with tarfile.open(out, "w:gz") as tar:
        tar.add(tmp, arcname=f"nomad-debug-{stamp}")
    shutil.rmtree(tmp, ignore_errors=True)
    print(f"==> Debug capture complete: {out} "
          f"({len(manifest['Files'])} files, "
          f"{len(manifest['Errors'])} errors)")


def cmd_monitor(args) -> None:
    """Stream agent logs (ref command/monitor.go)."""
    from .api import Client
    sdk = Client(timeout=3600)
    for line in sdk.agent.monitor(log_level=args.log_level):
        print(line)


def cmd_system_gc(args) -> None:
    api("PUT", "/v1/system/gc", {})
    print("==> GC triggered")


def cmd_system_reconcile_summaries(args) -> None:
    """ref command/system_reconcile_summaries.go"""
    api("PUT", "/v1/system/reconcile/summaries", {})
    print("==> Job summaries reconciled")


def cmd_acl_bootstrap(args) -> None:
    tok = api("POST", "/v1/acl/bootstrap")
    print(f"Accessor ID  = {tok['AccessorID']}")
    print(f"Secret ID    = {tok['SecretID']}")
    print(f"Name         = {tok['Name']}")
    print(f"Type         = {tok['Type']}")


def cmd_acl_policy_apply(args) -> None:
    with open(args.rules_file) as f:
        rules = f.read()
    api("PUT", f"/v1/acl/policy/{args.name}",
        {"Description": args.description or "", "Rules": rules})
    print(f"Successfully wrote ACL policy {args.name!r}")


def cmd_acl_policy_list(args) -> None:
    pols = api("GET", "/v1/acl/policies")
    if not pols:
        print("No policies")
        return
    _table([[p["Name"], p["Description"]] for p in pols],
           ["Name", "Description"])


def cmd_acl_policy_delete(args) -> None:
    api("DELETE", f"/v1/acl/policy/{args.name}")
    print(f"Successfully deleted ACL policy {args.name!r}")


def cmd_acl_token_create(args) -> None:
    tok = api("PUT", "/v1/acl/token", {
        "Name": args.name or "",
        "Type": args.type,
        "Policies": args.policy or [],
        "Global": bool(args.global_)})
    print(f"Accessor ID  = {tok['AccessorID']}")
    print(f"Secret ID    = {tok['SecretID']}")
    print(f"Type         = {tok['Type']}")
    print(f"Policies     = {tok['Policies']}")


def cmd_acl_token_list(args) -> None:
    toks = api("GET", "/v1/acl/tokens")
    _table([[t["AccessorID"][:8], t["Name"], t["Type"],
             ",".join(t["Policies"])] for t in toks],
           ["Accessor", "Name", "Type", "Policies"])


def cmd_acl_token_delete(args) -> None:
    api("DELETE", f"/v1/acl/token/{args.accessor_id}")
    print("Token deleted")


def cmd_acl_token_self(args) -> None:
    tok = api("GET", "/v1/acl/token/self")
    print(f"Accessor ID  = {tok['AccessorID']}")
    print(f"Name         = {tok['Name']}")
    print(f"Type         = {tok['Type']}")
    print(f"Policies     = {tok['Policies']}")


def cmd_namespace_apply(args) -> None:
    api("PUT", f"/v1/namespace/{args.name}",
        {"Name": args.name, "Description": args.description or ""})
    print(f"Successfully applied namespace {args.name!r}")


def cmd_namespace_list(args) -> None:
    nss = api("GET", "/v1/namespaces")
    _table([[n["Name"], n["Description"]] for n in nss],
           ["Name", "Description"])


def cmd_namespace_delete(args) -> None:
    api("DELETE", f"/v1/namespace/{args.name}")
    print(f"Successfully deleted namespace {args.name!r}")


def cmd_server_members(args) -> None:
    m = api("GET", "/v1/agent/members")
    _table([[x["Name"], x["Status"]] for x in m["Members"]],
           ["Name", "Status"])


def cmd_server_join(args) -> None:
    """ref command/server_join.go: gossip-join this agent to peers."""
    q = "&".join(f"address={urllib.parse.quote(a)}" for a in args.address)
    resp = api("PUT", f"/v1/agent/join?{q}")
    print(f"==> Joined {resp.get('num_joined', 0)} server(s)")


def cmd_scaling_policy(args) -> None:
    """ref command/scaling_policy_list.go / _info.go"""
    if args.policy_id:
        p = api("GET", f"/v1/scaling/policy/{args.policy_id}")
        print(json.dumps(p, indent=2))
    else:
        pols = api("GET", "/v1/scaling/policies")
        _table([[p["ID"][:8], (p.get("Target") or {}).get("Job", ""),
                 (p.get("Target") or {}).get("Group", ""),
                 "true" if p.get("Enabled") else "false"]
                for p in pols],
               ["ID", "Job", "Group", "Enabled"])


def cmd_version(args) -> None:
    from . import __version__
    print(f"nomad-tpu v{__version__}")


def cmd_status(args) -> None:
    me = api("GET", "/v1/agent/self")
    print(json.dumps(me.get("stats", {}), indent=2))


# ------------------------------------------------------------------ main

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    ag = sub.add_parser("agent")
    # value flags default to None (sentinel): cmd_agent applies only
    # explicitly passed flags over config files over AgentConfig defaults
    ag.add_argument("-dev", action="store_true")
    ag.add_argument("-port", type=int, default=None,
                    help="HTTP port (default 4646)")
    ag.add_argument("-data-dir", dest="data_dir", default=None)
    ag.add_argument("-workers", type=int, default=None,
                    help="scheduler workers (default 2)")
    ag.add_argument("-acl-enabled", dest="acl_enabled",
                    action="store_const", const=True, default=None)
    ag.add_argument("-region", default=None)
    ag.add_argument("-authoritative-region", dest="authoritative_region",
                    default=None)
    ag.add_argument("-rpc-port", dest="rpc_port", type=int, default=None)
    ag.add_argument("-gossip-port", dest="gossip_port", type=int,
                    default=None)
    ag.add_argument("-join", action="append", default=[],
                    help="gossip seed host:port (repeatable)")
    ag.add_argument("-bootstrap-expect", dest="bootstrap_expect", type=int,
                    default=None, help="N>1: wait for N servers then "
                    "bootstrap together; 1: bootstrap now; 0: wait to be "
                    "adopted by an existing leader")
    ag.add_argument("-replication-token", dest="replication_token",
                    default=None, help="management token of the "
                    "authoritative region (ACL replication)")
    ag.add_argument("-plugin-dir", dest="plugin_dir", default=None,
                    help="directory of external driver plugin executables")
    ag.add_argument("-config", action="append", default=[],
                    help="HCL/JSON agent config file or directory "
                    "(repeatable; merged in order, flags override)")
    ag.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job")
    jsub = job.add_subparsers(dest="job_cmd", required=True)
    jr = jsub.add_parser("run")
    jr.add_argument("spec")
    jr.add_argument("-detach", action="store_true")
    jr.add_argument("-var", action="append")
    jr.set_defaults(fn=cmd_job_run)
    jp = jsub.add_parser("plan")
    jp.add_argument("spec")
    jp.add_argument("-var", action="append")
    jp.add_argument("-verbose", action="store_true", dest="verbose",
                    help="show unchanged context fields in the diff")
    jp.set_defaults(fn=cmd_job_plan)
    jv = jsub.add_parser("validate")
    jv.add_argument("spec")
    jv.add_argument("-var", action="append")
    jv.set_defaults(fn=cmd_job_validate)
    ji = jsub.add_parser("inspect")
    ji.add_argument("job_id")
    ji.set_defaults(fn=cmd_job_inspect)
    js = jsub.add_parser("status")
    js.add_argument("job_id", nargs="?", default="")
    js.set_defaults(fn=cmd_job_status)
    jst = jsub.add_parser("stop")
    jst.add_argument("job_id")
    jst.add_argument("-purge", action="store_true")
    jst.set_defaults(fn=cmd_job_stop)
    jd = jsub.add_parser("dispatch")
    jd.add_argument("job_id")
    jd.add_argument("-meta", action="append")
    jd.set_defaults(fn=cmd_job_dispatch)
    jsc = jsub.add_parser("scale")
    jsc.add_argument("job_id")
    jsc.add_argument("group")
    jsc.add_argument("count")
    jsc.set_defaults(fn=cmd_job_scale)
    jrv = jsub.add_parser("revert")
    jrv.add_argument("job_id")
    jrv.add_argument("version")
    jrv.set_defaults(fn=cmd_job_revert)
    jh = jsub.add_parser("history")
    jh.add_argument("job_id")
    jh.set_defaults(fn=cmd_job_history)
    je = jsub.add_parser("eval")
    je.add_argument("job_id")
    je.add_argument("-force-reschedule", dest="force_reschedule",
                    action="store_true")
    je.set_defaults(fn=cmd_job_eval)
    jpf = jsub.add_parser("periodic")
    jpfsub = jpf.add_subparsers(dest="periodic_cmd", required=True)
    jpff = jpfsub.add_parser("force")
    jpff.add_argument("job_id")
    jpff.set_defaults(fn=cmd_job_periodic_force)
    jdps = jsub.add_parser("deployments")
    jdps.add_argument("job_id")
    jdps.set_defaults(fn=cmd_job_deployments)

    node = sub.add_parser("node")
    nsub = node.add_subparsers(dest="node_cmd", required=True)
    ns = nsub.add_parser("status")
    ns.add_argument("node_id", nargs="?", default="")
    ns.set_defaults(fn=cmd_node_status)
    nd = nsub.add_parser("drain")
    nd.add_argument("node_id")
    nd.add_argument("-enable", action="store_true")
    nd.add_argument("-disable", dest="enable", action="store_false")
    nd.add_argument("-deadline", type=float, default=3600.0)
    nd.add_argument("-ignore-system", dest="ignore_system",
                    action="store_true")
    nd.add_argument("-monitor", action="store_true",
                    help="block and stream drain progress until done")
    nd.set_defaults(fn=cmd_node_drain)
    ne = nsub.add_parser("eligibility")
    ne.add_argument("node_id")
    ne.add_argument("-enable", action="store_true")
    ne.add_argument("-disable", dest="enable", action="store_false")
    ne.set_defaults(fn=cmd_node_eligibility)

    alloc = sub.add_parser("alloc")
    asub = alloc.add_subparsers(dest="alloc_cmd", required=True)
    ast = asub.add_parser("status")
    ast.add_argument("alloc_id")
    ast.set_defaults(fn=cmd_alloc_status)
    aex = asub.add_parser("exec")
    aex.add_argument("alloc_id")
    aex.add_argument("-task", default="")
    aex.add_argument("-tty", action="store_true")
    aex.add_argument("command", nargs=argparse.REMAINDER)
    aex.set_defaults(fn=cmd_alloc_exec)
    asg = asub.add_parser("signal")
    asg.add_argument("alloc_id")
    asg.add_argument("-task", default="")
    asg.add_argument("-s", dest="signal", default="SIGUSR1")
    asg.set_defaults(fn=cmd_alloc_signal)
    ars = asub.add_parser("restart")
    ars.add_argument("alloc_id")
    ars.add_argument("-task", default="")
    ars.set_defaults(fn=cmd_alloc_restart)
    asp = asub.add_parser("stop")
    asp.add_argument("alloc_id")
    asp.set_defaults(fn=cmd_alloc_stop)
    afs = asub.add_parser("fs")
    afs.add_argument("alloc_id")
    afs.add_argument("path", nargs="?", default="/")
    afs.add_argument("-stat", action="store_true")
    afs.set_defaults(fn=cmd_alloc_fs)
    alg = asub.add_parser("logs")
    alg.add_argument("alloc_id")
    alg.add_argument("-task", default="")
    alg.add_argument("-stderr", action="store_true")
    alg.add_argument("-f", dest="follow", action="store_true")
    alg.set_defaults(fn=cmd_alloc_logs)

    ev = sub.add_parser("eval")
    esub = ev.add_subparsers(dest="eval_cmd", required=True)
    est = esub.add_parser("status")
    est.add_argument("eval_id")
    est.set_defaults(fn=cmd_eval_status)
    eli = esub.add_parser("list")
    eli.add_argument("-limit", type=int, default=50)
    eli.set_defaults(fn=cmd_eval_list)

    dep = sub.add_parser("deployment")
    dep.add_argument("action",
                     choices=["list", "status", "promote", "fail",
                              "pause", "resume"])
    dep.add_argument("id", nargs="?", default="")
    dep.set_defaults(fn=cmd_deployment)

    aclp = sub.add_parser("acl")
    aclsub = aclp.add_subparsers(dest="acl_cmd", required=True)
    ab = aclsub.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl_bootstrap)
    apol = aclsub.add_parser("policy")
    apolsub = apol.add_subparsers(dest="policy_cmd", required=True)
    apa = apolsub.add_parser("apply")
    apa.add_argument("name")
    apa.add_argument("rules_file")
    apa.add_argument("-description", default="")
    apa.set_defaults(fn=cmd_acl_policy_apply)
    apl = apolsub.add_parser("list")
    apl.set_defaults(fn=cmd_acl_policy_list)
    apd = apolsub.add_parser("delete")
    apd.add_argument("name")
    apd.set_defaults(fn=cmd_acl_policy_delete)
    atok = aclsub.add_parser("token")
    atoksub = atok.add_subparsers(dest="token_cmd", required=True)
    atc = atoksub.add_parser("create")
    atc.add_argument("-name", default="")
    atc.add_argument("-type", default="client")
    atc.add_argument("-policy", action="append")
    atc.add_argument("-global", dest="global_", action="store_true")
    atc.set_defaults(fn=cmd_acl_token_create)
    atl = atoksub.add_parser("list")
    atl.set_defaults(fn=cmd_acl_token_list)
    ats = atoksub.add_parser("self")
    ats.set_defaults(fn=cmd_acl_token_self)
    atd = atoksub.add_parser("delete")
    atd.add_argument("accessor_id")
    atd.set_defaults(fn=cmd_acl_token_delete)

    nsp = sub.add_parser("namespace")
    nssub = nsp.add_subparsers(dest="ns_cmd", required=True)
    nsa = nssub.add_parser("apply")
    nsa.add_argument("name")
    nsa.add_argument("-description", default="")
    nsa.set_defaults(fn=cmd_namespace_apply)
    nsl = nssub.add_parser("list")
    nsl.set_defaults(fn=cmd_namespace_list)
    nsd = nssub.add_parser("delete")
    nsd.add_argument("name")
    nsd.set_defaults(fn=cmd_namespace_delete)

    op = sub.add_parser("operator")
    osub = op.add_subparsers(dest="op_cmd", required=True)
    osch = osub.add_parser("scheduler")
    osch.add_argument("action", choices=["get-config", "set-config"])
    osch.add_argument("-scheduler-algorithm", dest="scheduler_algorithm",
                      default="")
    osch.add_argument("-memory-oversubscription",
                      dest="memory_oversubscription",
                      choices=["true", "false"], default=None)
    osch.set_defaults(fn=cmd_operator_scheduler)
    oraft = osub.add_parser("raft")
    oraft.add_argument("action", choices=["list-peers", "remove-peer"])
    oraft.add_argument("-peer-id", dest="peer_id", default="")
    oraft.add_argument("-peer-address", dest="peer_address", default="")
    oraft.set_defaults(fn=cmd_operator_raft)
    osnap = osub.add_parser("snapshot")
    osnap.add_argument("action", choices=["save", "restore", "inspect"])
    osnap.add_argument("file")
    osnap.set_defaults(fn=cmd_operator_snapshot)
    oap = osub.add_parser("autopilot")
    oap.add_argument("action", choices=["get-config", "set-config", "health"])
    oap.add_argument("-cleanup-dead-servers", dest="cleanup_dead_servers",
                     choices=["true", "false"], default=None)
    oap.set_defaults(fn=cmd_operator_autopilot)
    odbg = osub.add_parser("debug")
    odbg.add_argument("-duration", default="2",
                      help="seconds of periodic capture (default 2)")
    odbg.add_argument("-interval", default="1",
                      help="seconds between metric samples (default 1)")
    odbg.add_argument("-output", default="",
                      help="bundle path (default nomad-debug-<ts>.tar.gz)")
    odbg.set_defaults(fn=cmd_operator_debug)

    system = sub.add_parser("system")
    ssub = system.add_subparsers(dest="sys_cmd", required=True)
    sgc = ssub.add_parser("gc")
    sgc.set_defaults(fn=cmd_system_gc)
    srs = ssub.add_parser("reconcile")
    srssub = srs.add_subparsers(dest="reconcile_cmd", required=True)
    srss = srssub.add_parser("summaries")
    srss.set_defaults(fn=cmd_system_reconcile_summaries)

    srv = sub.add_parser("server")
    srvsub = srv.add_subparsers(dest="srv_cmd", required=True)
    sm = srvsub.add_parser("members")
    sm.set_defaults(fn=cmd_server_members)
    sfl = srvsub.add_parser("force-leave")
    sfl.add_argument("name")
    sfl.set_defaults(fn=cmd_server_force_leave)
    sj = srvsub.add_parser("join")
    sj.add_argument("address", nargs="+")
    sj.set_defaults(fn=cmd_server_join)

    scal = sub.add_parser("scaling")
    scalsub = scal.add_subparsers(dest="scaling_cmd", required=True)
    scp = scalsub.add_parser("policy")
    scp.add_argument("policy_id", nargs="?", default="")
    scp.set_defaults(fn=cmd_scaling_policy)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=cmd_version)

    st = sub.add_parser("status")
    st.set_defaults(fn=cmd_status)

    mon = sub.add_parser("monitor")
    mon.add_argument("-log-level", dest="log_level", default="info")
    mon.set_defaults(fn=cmd_monitor)

    vol = sub.add_parser("volume")
    vsub = vol.add_subparsers(dest="volume_cmd", required=True)
    vs = vsub.add_parser("status")
    vs.add_argument("volume_id", nargs="?", default="")
    vs.set_defaults(fn=cmd_volume_status)
    vr = vsub.add_parser("register")
    vr.add_argument("spec")
    vr.set_defaults(fn=cmd_volume_register)
    vd = vsub.add_parser("deregister")
    vd.add_argument("volume_id")
    vd.add_argument("-force", action="store_true")
    vd.set_defaults(fn=cmd_volume_deregister)
    vdt = vsub.add_parser("detach")
    vdt.add_argument("volume_id")
    vdt.add_argument("node_id")
    vdt.set_defaults(fn=cmd_volume_detach)

    plug = sub.add_parser("plugin")
    psub = plug.add_subparsers(dest="plugin_cmd", required=True)
    ps = psub.add_parser("status")
    ps.add_argument("plugin_id", nargs="?", default="")
    ps.set_defaults(fn=cmd_plugin_status)

    tr = sub.add_parser("trace")
    tr.add_argument("ref", nargs="?", default="",
                    help="eval id, trace id, or unique prefix; "
                         "omit to list")
    tr.add_argument("-limit", type=int, default=50)
    tr.add_argument("-chrome", default="",
                    help="write Chrome trace-event JSON to this file")
    tr.set_defaults(fn=cmd_trace)
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
