#!/usr/bin/env python
"""Headline benchmark (BASELINE.json): place a 50k-task batch job across a
simulated 10k-node cluster on TPU; target <1s wall-clock.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": target/value}

The measured region is the full solve path the tpu-batch scheduler algorithm
runs per evaluation: host->device transfer of the node matrices, the
feasibility-masked capacity + scoring + greedy placement kernel, and the
placement-count readback. (Alloc-object materialization and Raft apply are
the control plane's cost, unchanged from the reference design — see
SURVEY.md north star: plan_apply stays untouched.)
"""
import json
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 50_000
TARGET_S = 1.0


def build_cluster(n_nodes: int, seed: int = 42):
    """Synthetic heterogeneous fleet (the scheduler/benchmarks analog:
    ref scheduler/benchmarks/benchmarks_test.go:26 seeds 5k nodes)."""
    from nomad_tpu.solver import NUM_XR
    rng = np.random.default_rng(seed)
    cap = np.zeros((n_nodes, NUM_XR), np.float32)
    cap[:, 0] = rng.choice([4_000, 8_000, 16_000, 32_000], n_nodes)   # cpu MHz
    cap[:, 1] = rng.choice([8_192, 16_384, 32_768, 65_536], n_nodes)  # mem MB
    cap[:, 2] = 500_000                                               # disk MB
    cap[:, 3] = 12_001                                                # dyn ports
    cap[:, 4] = 10_000                                                # mbits
    used = np.zeros_like(cap)
    # background utilization: ~30% of nodes run other work
    busy = rng.random(n_nodes) < 0.3
    used[busy, 0] = rng.integers(500, 3_000, busy.sum())
    used[busy, 1] = rng.integers(1_024, 6_000, busy.sum())
    # irregular-constraint feasibility mask (pre-lowered host-side)
    feasible = rng.random(n_nodes) < 0.95
    return cap, used, feasible


def main() -> None:
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack

    cap_np, used_np, feas_np = build_cluster(N_NODES)
    ask_np = np.zeros(NUM_XR, np.float32)
    ask_np[0], ask_np[1], ask_np[2] = 250.0, 512.0, 300.0   # batch task ask

    solve = jax.jit(fill_greedy_binpack)

    # warmup / compile (cached afterwards)
    placed = solve(jnp.asarray(cap_np), jnp.asarray(used_np),
                   jnp.asarray(ask_np), jnp.int32(N_TASKS),
                   jnp.asarray(feas_np))
    placed.block_until_ready()

    # measured: transfer + solve + readback, median of 5
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        placed = solve(jnp.asarray(cap_np), jnp.asarray(used_np),
                       jnp.asarray(ask_np), jnp.int32(N_TASKS),
                       jnp.asarray(feas_np))
        counts = np.asarray(placed)
        times.append(time.perf_counter() - t0)
    value = float(np.median(times))

    # validity: full placement, no node overcommitted
    total = int(counts.sum())
    free = cap_np - used_np
    ok_dims = (used_np + counts[:, None] * ask_np[None, :] <= cap_np + 1e-3)
    assert total == N_TASKS, f"placed {total}/{N_TASKS}"
    assert bool(ok_dims.all()), "overcommit detected"
    assert int(counts[~feas_np].sum()) == 0, "placed on infeasible node"

    print(json.dumps({
        "metric": f"{N_TASKS//1000}k-task batch placement on "
                  f"{N_NODES//1000}k-node sim ({jax.devices()[0].platform})",
        "value": round(value, 6),
        "unit": "s",
        "vs_baseline": round(TARGET_S / value, 2),
    }))


if __name__ == "__main__":
    main()
