#!/usr/bin/env python
"""Headline benchmark (BASELINE.json): place a 50k-task batch job across a
simulated 10k-node cluster on TPU; target <1s wall-clock.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": target/value}

The measured region is the full solve path the tpu-batch scheduler algorithm
runs per evaluation: host->device transfer of the node matrices, the
feasibility-masked capacity + scoring + greedy placement kernel, and the
placement-count readback. (Alloc-object materialization and Raft apply are
the control plane's cost, unchanged from the reference design — see
SURVEY.md north star: plan_apply stays untouched.)
"""
import json
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 50_000
TARGET_S = 1.0


def build_cluster(n_nodes: int, seed: int = 42):
    """Synthetic heterogeneous fleet (the scheduler/benchmarks analog:
    ref scheduler/benchmarks/benchmarks_test.go:26 seeds 5k nodes)."""
    from nomad_tpu.solver import NUM_XR
    rng = np.random.default_rng(seed)
    cap = np.zeros((n_nodes, NUM_XR), np.float32)
    cap[:, 0] = rng.choice([4_000, 8_000, 16_000, 32_000], n_nodes)   # cpu MHz
    cap[:, 1] = rng.choice([8_192, 16_384, 32_768, 65_536], n_nodes)  # mem MB
    cap[:, 2] = 500_000                                               # disk MB
    cap[:, 3] = 12_001                                                # dyn ports
    cap[:, 4] = 10_000                                                # mbits
    used = np.zeros_like(cap)
    # background utilization: ~30% of nodes run other work
    busy = rng.random(n_nodes) < 0.3
    used[busy, 0] = rng.integers(500, 3_000, busy.sum())
    used[busy, 1] = rng.integers(1_024, 6_000, busy.sum())
    # irregular-constraint feasibility mask (pre-lowered host-side)
    feasible = rng.random(n_nodes) < 0.95
    return cap, used, feasible


def _bench(fn, *host_args, reps: int = 5) -> tuple[float, "np.ndarray"]:
    """Median wall-clock of transfer + solve + readback.

    host_args stay on the host (numpy/python scalars); each timed rep pays
    the device transfer via jnp.asarray, matching the per-evaluation cost
    the scheduler path pays (module docstring)."""
    import jax.numpy as jnp

    def put():
        return [jnp.asarray(a) if isinstance(a, np.ndarray) else a
                for a in host_args]
    out = fn(*put())
    np.asarray(out)                      # warmup/compile
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*put())
        counts = np.asarray(out)
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), counts


def config2() -> dict:
    """BASELINE config 2: 1k-task batch / 500 sim nodes, cpu+mem."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    cap, used, feas = build_cluster(500)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 100.0, 256.0
    solve = jax.jit(fill_greedy_binpack)
    value, counts = _bench(solve, cap, used, ask, jnp.int32(1_000), feas)
    assert int(counts.sum()) == 1_000
    return {"metric": "cfg2: 1k-task batch / 500 nodes", "value":
            round(value, 6), "unit": "s",
            "vs_baseline": round(1.0 / value, 2)}


def config3() -> dict:
    """BASELINE config 3: 10k-task batch / 2k nodes with spread +
    anti-affinity + distinct_hosts (the interacting-score scan path)."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR
    from nomad_tpu.solver.kernels import place_chunked
    rng = np.random.default_rng(7)
    n_nodes, n_tasks = 2_000, 10_000
    cap, used, feas = build_cluster(n_nodes, seed=7)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 100.0, 128.0
    racks = rng.integers(0, 100, n_nodes)          # spread property: rack
    prop_counts = np.zeros(100, np.int32)
    solve = jax.jit(lambda *a: place_chunked(
        *a, max_per_node=8, max_steps=256))        # distinct-ish cap
    value, counts = _bench(
        solve, cap, used, ask, jnp.int32(n_tasks), feas,
        np.zeros(n_nodes, np.int32), jnp.int32(n_tasks),
        racks.astype(np.int32), prop_counts, jnp.float32(50.0))
    assert int(counts.sum()) == n_tasks, f"placed {counts.sum()}"
    assert int(counts.max()) <= 8
    return {"metric": "cfg3: 10k tasks / 2k nodes spread+anti-affinity",
            "value": round(value, 6), "unit": "s",
            "vs_baseline": round(1.0 / value, 2)}


def config4() -> dict:
    """BASELINE config 4: mixed service+batch with device asks +
    preemption on 5k nodes."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    from nomad_tpu.solver.kernels import preempt_top_k
    rng = np.random.default_rng(11)
    n_nodes = 5_000
    cap, used, feas = build_cluster(n_nodes, seed=11)
    batch_ask = np.zeros(NUM_XR, np.float32)
    batch_ask[0], batch_ask[1] = 400.0, 1024.0
    svc_ask = np.zeros(NUM_XR, np.float32)
    svc_ask[0], svc_ask[1] = 2000.0, 4096.0
    # device asks enter the solver as a pre-lowered feasibility mask
    # (SURVEY.md §7.4: irregular constraints and device groups tensorize to
    # per-node bits; exact instance ids assigned host-side) — the service
    # wave only fits on the ~20%% of nodes fingerprinting the device
    has_device = rng.random(n_nodes) < 0.2

    solve = jax.jit(fill_greedy_binpack)
    preempt = jax.jit(preempt_top_k)

    def run(cap_j, used_j, feas_j, dev_j):
        placed = solve(cap_j, used_j, jnp.asarray(batch_ask),
                       jnp.int32(15_000), feas_j)
        used2 = used_j + placed[:, None] * jnp.asarray(batch_ask)[None, :]
        # high-priority service wave with device ask; preemption pass on
        # the tightest node
        svc = solve(cap_j, used2, jnp.asarray(svc_ask), jnp.int32(500),
                    feas_j & dev_j)
        # victims on node 0: its batch placements
        victims = jnp.tile(jnp.asarray(batch_ask)[None, :], (64, 1))
        vprio = jnp.full((64,), 50, jnp.int32)
        mask = preempt(victims, vprio, jnp.asarray(svc_ask),
                       cap_j[0] - used2[0], jnp.int32(80))
        return svc + jnp.zeros_like(placed).at[0].set(
            mask.sum().astype(jnp.int32) * 0)
    value, counts = _bench(run, cap, used, feas, has_device)
    assert int(counts.sum()) >= 500
    return {"metric":
            "cfg4: mixed service+batch, device-masked + preemption, "
            "5k nodes",
            "value": round(value, 6), "unit": "s",
            "vs_baseline": round(1.0 / value, 2)}


def config5() -> dict:
    """BASELINE config 5: C2M-style replay — 2M tasks across 10k nodes as
    200 sequential 10k-task evals with running usage (multi-job stream,
    the C2M 'containers scheduled' analog). Reports evals/sec."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    n_nodes, evals, tasks_per = 10_000, 200, 10_000
    cap, used, feas = build_cluster(n_nodes)
    # C2M containers are tiny (the challenge used minimal redis containers)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 1.0, 1.0

    @jax.jit
    def eval_stream(cap_j, used_j, feas_j):
        def one(used_acc, _):
            placed = fill_greedy_binpack(cap_j, used_acc, jnp.asarray(ask),
                                         jnp.int32(tasks_per), feas_j)
            return used_acc + placed[:, None] * jnp.asarray(ask)[None, :], \
                placed.sum()
        _, placed_counts = jax.lax.scan(one, used_j, None, length=evals)
        return placed_counts

    value, counts = _bench(eval_stream, cap, used, feas, reps=3)
    total = int(counts.sum())
    assert total == evals * tasks_per, f"placed {total}"
    # vs_baseline uses the same <1s-per-eval-stream convention as the other
    # configs; the quota/federation parts of BASELINE cfg5 are control-plane
    # behavior outside this solver microbench's scope
    return {"metric": "cfg5: C2M-style eval stream, 2M tasks / 10k nodes "
            f"({evals} evals)", "value": round(value, 6), "unit": "s",
            "evals_per_sec": round(evals / value, 1),
            "tasks_per_sec": round(total / value, 0),
            "vs_baseline": round(TARGET_S / value, 2)}


def main() -> None:
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack

    cap_np, used_np, feas_np = build_cluster(N_NODES)
    ask_np = np.zeros(NUM_XR, np.float32)
    ask_np[0], ask_np[1], ask_np[2] = 250.0, 512.0, 300.0   # batch task ask

    solve = jax.jit(fill_greedy_binpack)

    # warmup / compile (cached afterwards)
    placed = solve(jnp.asarray(cap_np), jnp.asarray(used_np),
                   jnp.asarray(ask_np), jnp.int32(N_TASKS),
                   jnp.asarray(feas_np))
    placed.block_until_ready()

    # measured: transfer + solve + readback, median of 5
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        placed = solve(jnp.asarray(cap_np), jnp.asarray(used_np),
                       jnp.asarray(ask_np), jnp.int32(N_TASKS),
                       jnp.asarray(feas_np))
        counts = np.asarray(placed)
        times.append(time.perf_counter() - t0)
    value = float(np.median(times))

    # validity: full placement, no node overcommitted
    total = int(counts.sum())
    free = cap_np - used_np
    ok_dims = (used_np + counts[:, None] * ask_np[None, :] <= cap_np + 1e-3)
    assert total == N_TASKS, f"placed {total}/{N_TASKS}"
    assert bool(ok_dims.all()), "overcommit detected"
    assert int(counts[~feas_np].sum()) == 0, "placed on infeasible node"

    print(json.dumps({
        "metric": f"{N_TASKS//1000}k-task batch placement on "
                  f"{N_NODES//1000}k-node sim ({jax.devices()[0].platform})",
        "value": round(value, 6),
        "unit": "s",
        "vs_baseline": round(TARGET_S / value, 2),
    }))


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "--config":
        which = sys.argv[2] if len(sys.argv) > 2 else "all"
        fns = {"2": config2, "3": config3, "4": config4, "5": config5}
        for key, fn in fns.items():
            if which in (key, "all"):
                print(json.dumps(fn()))
    else:
        main()   # driver contract: exactly one JSON line
