#!/usr/bin/env python
"""Headline benchmark (BASELINE.json): place a 50k-task batch job across a
simulated 10k-node cluster THROUGH THE REAL SCHEDULER PATH on TPU;
target <1s wall-clock.

Measured region (the full worker path, VERDICT r1 next #1):
  eval -> GenericScheduler.process -> reconciler -> SolverPlacer
  (dense tensorize from the store's incremental usage index + TPU kernel +
  batched alloc materialization) -> real serial Planner.apply_plan
  (vectorized per-node re-check) -> FSM commit into the state store.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": target/value, ...}
extra keys: compile_s, rejection parity vs the host binpack oracle, and a
measured host-path comparison (host is timed at 5k tasks — it is linear in
placements, the extrapolation to 50k is reported separately).

`--config 2..5` runs the BASELINE kernel micro-configs; `--kernel` runs the
round-1 kernel-only solve for comparison.
"""
import json
import os
import sys
import threading
import time

# the bench exercises the sharded tier wherever it runs: force the
# 8-way virtual host mesh (the tier-1 conftest does the same). The flag
# only affects the CPU platform — on a real TPU/GPU box the accelerator
# devices are untouched. Must happen before jax initializes a backend.
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

N_NODES = 10_000
N_TASKS = 50_000
TARGET_S = 1.0

STREAM_EVALS = 16
STREAM_CONCURRENCY = 16     # worker threads serving the 1k-eval stream
STREAM_WINDOW_MS = 15.0     # eval coalescing window for the stream burst

# state writes from bench shims (index mint + upsert) are not atomic in
# the store; the concurrent stream workers serialize them here the way
# the real server serializes through raft
_STATE_WRITE_LOCK = threading.Lock()


# ---------------------------------------------------------------- cluster sim

def _mk_node(i: int, rng):
    """Heterogeneous fleet node (scheduler/benchmarks analog:
    ref scheduler/benchmarks/benchmarks_test.go:26 seeds 5k nodes)."""
    from nomad_tpu import mock
    n = mock.node()
    n.name = f"bench-{i}"
    n.node_class = f"c{int(rng.integers(0, 4))}"
    n.node_resources.cpu.cpu_shares = int(
        rng.choice([4_000, 8_000, 16_000, 32_000]))
    n.node_resources.memory.memory_mb = int(
        rng.choice([8_192, 16_384, 32_768, 65_536]))
    n.node_resources.disk.disk_mb = 500_000
    return n


def _mk_batch_job(job_id: str, count: int, cpu=250, mem=512, disk=300):
    from nomad_tpu import mock
    job = mock.batch_job()
    job.id = job.name = job_id
    tg = job.task_groups[0]
    tg.count = count
    tg.ephemeral_disk.size_mb = disk
    task = tg.tasks[0]
    task.resources.cpu = cpu
    task.resources.memory_mb = mem
    task.resources.networks = []
    tg.networks = []
    return job


def _seed_fsm(n_nodes: int, algorithm: str, seed: int = 42,
              pin_ids: str = ""):
    """`pin_ids` gives nodes deterministic ids (`<prefix><i>`): node ids
    key store iteration order, so differential runs that must place
    bit-identically across processes/legs pin them (mock ids come from
    urandom otherwise)."""
    from nomad_tpu.server.fsm import NomadFSM
    from nomad_tpu.structs import SchedulerConfiguration
    rng = np.random.default_rng(seed)
    fsm = NomadFSM()
    s = fsm.state
    s.set_scheduler_config(
        1, SchedulerConfiguration(scheduler_algorithm=algorithm))
    for i in range(n_nodes):
        n = _mk_node(i, rng)
        if pin_ids:
            n.id = f"{pin_ids}{i:06d}"
        s.upsert_node(i + 2, n)
    return fsm


class _WorkerShim:
    """Planner-interface glue a server Worker provides (ref nomad/worker.go
    SubmitPlan/UpdateEval/CreateEval), over the real serial applier.

    When the Planner's applier thread is running, plans route through its
    queue (the production path — and what the pipelined plan lifecycle
    overlaps against); otherwise they apply inline, which keeps the
    single-threaded sections (warmup, rejection sims) deterministic."""

    def __init__(self, planner, state):
        self.planner = planner
        self.state = state
        self.submissions = []           # (plan, result) pairs
        self.async_submissions = []     # (plan, pending) — resolved lazily

    def _queue_alive(self) -> bool:
        t = getattr(self.planner, "_thread", None)
        return t is not None and t.is_alive()

    def submit_plan(self, plan):
        if self._queue_alive():
            result = self.planner.submit_plan(plan, timeout=120.0)
        else:
            result = self.planner.apply_plan(plan)
        self.submissions.append((plan, result))
        return result

    def submit_plan_async(self, plan):
        """Pipelined chunk submit: enqueue on the live applier thread, or
        apply inline and hand back an already-resolved pending."""
        if self._queue_alive():
            pending = self.planner.submit_plan_async(plan)
        else:
            from nomad_tpu.server.plan_apply import _PendingPlan
            pending = _PendingPlan(plan)
            try:
                pending.respond(self.planner.apply_plan(plan), None)
            except Exception as e:      # noqa: BLE001 — report to caller
                pending.respond(None, str(e))
        self.async_submissions.append((plan, pending))
        return pending

    def all_submissions(self):
        """submissions incl. resolved async chunk plans (the placer waits
        out every pending before its eval returns, so wait(0) suffices)."""
        out = list(self.submissions)
        for plan, pending in self.async_submissions:
            result, _ = pending.wait(0)
            out.append((plan, result))
        return out

    def update_eval(self, ev):
        with _STATE_WRITE_LOCK:
            self.state.upsert_evals(self.state.latest_index() + 1, [ev])

    def create_eval(self, ev):
        with _STATE_WRITE_LOCK:
            self.state.upsert_evals(self.state.latest_index() + 1, [ev])

    def refresh_snapshot(self, old):
        return self.state.snapshot()


def _run_eval(fsm, planner, job, snap=None, sched_type="batch",
              eval_id=None):
    """One eval through scheduler + real plan applier. Returns (shim, eval).
    `eval_id` pins the per-eval RNG (the placer's shuffle/jitter seed from
    the stack rng, DET001) — differentials and the parity fuzz tests pass
    a fixed id so identical inputs place identically run to run."""
    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.structs import Evaluation, new_id
    s = fsm.state
    ev = Evaluation(id=eval_id or new_id(), namespace="default",
                    job_id=job.id, type=sched_type, priority=50)
    s.upsert_evals(s.latest_index() + 1, [ev])
    shim = _WorkerShim(planner, s)
    sched = new_scheduler(sched_type, snap or s.snapshot(), shim)
    sched.process(ev)
    return shim, sched


def _register(fsm, job):
    fsm.state.upsert_job(fsm.state.latest_index() + 1, job)


def _validate(fsm, job_id: str, expect: int) -> None:
    s = fsm.state
    placed = [a for a in s.iter_allocs() if a.job_id == job_id]
    assert len(placed) == expect, f"placed {len(placed)}/{expect}"
    view = s.usage.view()
    over = view.used > view.cap + 1e-3
    assert not bool(over.any()), "overcommit detected in committed state"


def _rejection_stats(shims) -> tuple[int, int]:
    """(rejected nodes, total plan nodes) across all submissions,
    including async-submitted pipelined chunk plans."""
    rejected = 0
    total = 0
    for shim in shims:
        for plan, result in shim.all_submissions():
            if result is None:
                continue
            total += len(plan.node_allocation)
            rejected += len(result.rejected_nodes)
    return rejected, total


def _concurrent_rejection_rate(algorithm: str, n_jobs: int = 8,
                               tasks_per: int = 2_000,
                               n_nodes: int = 2_000,
                               seed: int = 20260729) -> tuple[float, float]:
    """Optimistic-concurrency conflict sim: N workers schedule different
    jobs from the SAME stale snapshot (the reference's per-core workers,
    nomad/worker.go), plans land serially on the real applier which
    re-checks against latest state (plan_apply.go:638). Returns
    (node_rejection_rate, alloc_rejection_rate) — the plan-rejection
    rate BASELINE's second headline metric asks for (the reference's
    `nomad.plan.node_rejected` is per node; the alloc-weighted rate
    additionally measures wasted placement work and does not reward
    schedulers that submit tighter plans)."""
    import random as _random
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner

    _random.seed(seed)
    fsm = _seed_fsm(n_nodes, algorithm, seed=7)
    planner = Planner(RaftLog(fsm), fsm.state)
    jobs = []
    for j in range(n_jobs):
        # asks sized so the combined load contends for the same best nodes
        job = _mk_batch_job(f"conc-{j}", tasks_per, cpu=400, mem=700)
        _register(fsm, job)
        jobs.append(job)
    stale = fsm.state.snapshot()          # every "worker" plans against this
    rn = tn = ra = ta = 0
    for job in jobs:
        shim, _ = _run_eval(fsm, planner, job, snap=stale)
        for plan, result in shim.submissions:
            if result is None:
                continue
            tn += len(plan.node_allocation)
            rn += len(result.rejected_nodes)
            ta += sum(len(v) for v in plan.node_allocation.values())
            ra += sum(len(plan.node_allocation[nid])
                      for nid in result.rejected_nodes)
    return (rn / tn if tn else 0.0), (ra / ta if ta else 0.0)


# ------------------------------------------------------------------ headline

def _warmup_compile() -> float:
    """Pay every one-time XLA compile the measured paths use; -> seconds.
    Same node count as the measured runs (=> same padded kernel bucket).
    BOTH depth regimes are warmed — the tiny job hits the jittered
    sampled-grid artifact (host tier), the 16k job the deterministic
    full-curve artifact on the accelerator (m = 2*16k/10k > 3), which is
    what the measured 50k run uses."""
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.structs import SCHED_ALG_TPU
    t0 = time.perf_counter()
    fsm_w = _seed_fsm(N_NODES, SCHED_ALG_TPU)
    planner_w = Planner(RaftLog(fsm_w), fsm_w.state)
    _warmup_evals(fsm_w, planner_w)
    return time.perf_counter() - t0


def _warmup_evals(fsm_w, planner_w) -> None:
    # three artifacts: jittered-grid on the host tier (tiny count),
    # jittered-grid on the accelerator (mid count), deterministic full
    # curve on the accelerator (m > 3)
    for wname, wcount in (("warmup", 100), ("warmup-mid", 5_000),
                          ("warmup-det", 16_000)):
        job_w = _mk_batch_job(wname, wcount)
        _register(fsm_w, job_w)
        _run_eval(fsm_w, planner_w, job_w)
        _validate(fsm_w, wname, wcount)


def _stream_run(fsm_s, n_evals: int, concurrency: int,
                eval_ids: list = None) -> list:
    """Drive `n_evals` 1k-task evals through `concurrency` scheduler
    worker threads against fsm_s, plans landing on a LIVE serial applier
    (the production shape: per-core workers + leader-serial plan_apply).
    Jobs and eval records are seeded single-threaded before timing; the
    threads only schedule and submit. Returns per-eval submit-to-applied
    seconds, unordered."""
    from collections import deque

    from nomad_tpu.scheduler import new_scheduler
    from nomad_tpu.obs import trace as obs_trace
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.structs import (
        Evaluation, SchedulerConfiguration, SCHED_ALG_TPU, new_id,
    )
    from nomad_tpu.solver import microbatch

    s = fsm_s.state
    # stream-shaped coalescing window via the hot-reloadable operator
    # knob (the same runtime-mutation path the SchedulerAlgorithm enum
    # rides): every eval reads the latest config through its EvalContext
    s.set_scheduler_config(
        s.latest_index() + 1,
        SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU,
                               eval_batch_window_ms=STREAM_WINDOW_MS))
    planner_s = Planner(RaftLog(fsm_s), s)
    planner_s.start()
    work = deque()
    for j in range(n_evals):
        job_s = _mk_batch_job(f"stream-{j}", 1_000)
        _register(fsm_s, job_s)
        ev = Evaluation(id=new_id(), namespace="default", job_id=job_s.id,
                        type="batch", priority=50)
        s.upsert_evals(s.latest_index() + 1, [ev])
        work.append(ev)
        if eval_ids is not None:
            eval_ids.append(ev.id)
    times: list = []
    errors: list = []
    # the production path pushes the eval broker's dequeued-but-unacked
    # count into the micro-batcher so the FIRST solve of a burst knows
    # siblings are coming; the bench bypasses the broker, so its workers
    # feed the same hint themselves — without this every stream solve saw
    # concurrency<=1 and took the solo host-tier fast path, pinning
    # backend_tiers_stream to host (ISSUE 4 satellite, BENCH_r05 host=16)
    outstanding = [n_evals]
    out_lock = threading.Lock()
    microbatch.broker_in_flight(n_evals)

    def _eval_done():
        with out_lock:
            outstanding[0] -= 1
            microbatch.broker_in_flight(outstanding[0])

    def worker():
        while True:
            try:
                ev = work.popleft()         # deque.popleft is atomic
            except IndexError:
                return
            t0 = time.perf_counter()
            # mirror the production worker's trace lifecycle (ISSUE 7):
            # root at pickup, worker.invoke wrapping the scheduler, root
            # ended with the disposition — the bench bypasses the broker,
            # so it begins the trace itself (begin_eval is idempotent)
            ctx = obs_trace.begin_eval(ev.id, "eval", job=ev.job_id,
                                       type=ev.type)
            try:
                with obs_trace.use(ctx), \
                        obs_trace.span("worker.invoke", type=ev.type):
                    shim = _WorkerShim(planner_s, s)
                    sched = new_scheduler("batch", s.snapshot(), shim)
                    sched.process(ev)
            except BaseException as e:      # noqa: BLE001 — fail the bench
                obs_trace.end_eval(ev.id, "error", error=repr(e)[:200])
                errors.append(e)
                _eval_done()
                return
            obs_trace.end_eval(ev.id, "ok")
            times.append(time.perf_counter() - t0)
            _eval_done()

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"stream-worker-{i}")
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    planner_s.stop()
    microbatch.broker_in_flight(0)
    # a silently-shorter stream would overstate evals/sec and poison the
    # regression gate's recorded best — fail loudly instead
    if errors:
        raise RuntimeError(
            f"{len(errors)} stream worker(s) failed") from errors[0]
    if len(times) != n_evals:
        raise RuntimeError(f"stream completed {len(times)}/{n_evals} evals")
    return times


def _overload_run() -> dict:
    """Overload lineage (ISSUE 8): a 10x offered-load burst against the
    10k-node sim through a REAL Server (broker cap + shed, worker
    deadline drop, applier deadline gate, pressure ticks). Phases:

      steady   register jobs one at a time, each waiting for completion
               -> the sustainable per-eval rate (the goodput yardstick);
      burst    offer 10x that rate for a fixed window; the depth cap
               sheds the excess (lowest priority first) and the enqueue
               TTL expires work that outlived its caller;
      recover  burst stops; measure how long the backlog takes to drain.

    Records goodput (completed within deadline)/s, shed/expired counts,
    pressure transitions, max depth vs cap, recovery seconds, and an
    expired-evals-committed audit (must be 0: an expired eval may never
    reach a raft entry). Gated in tests/test_bench_regression.py once a
    BENCH_*.json carries the block."""
    from nomad_tpu.metrics import metrics
    from nomad_tpu.obs import trace as obs_trace
    from nomad_tpu.server import Server
    from nomad_tpu.structs import SCHED_ALG_TPU, SchedulerConfiguration

    deadline_s = 5.0
    cap = 64
    burst_window_s = 3.0
    tasks_per_job = 500

    s = Server(num_workers=STREAM_CONCURRENCY, gc_interval=9999)
    s.eval_broker.initial_nack_delay = 0.05
    s.eval_broker.subsequent_nack_delay = 0.2
    st = s.state
    st.set_scheduler_config(1, SchedulerConfiguration(
        scheduler_algorithm=SCHED_ALG_TPU,
        eval_batch_window_ms=STREAM_WINDOW_MS,
        broker_depth_cap=cap,
        eval_deadline_s=deadline_s))
    rng = np.random.default_rng(8)
    for i in range(N_NODES):
        st.upsert_node(i + 2, _mk_node(i, rng))
    obs_trace.configure(enabled=True, sample_rate=1.0)
    s.start()
    try:
        def register(name: str, priority: int) -> str:
            job = _mk_batch_job(name, tasks_per_job)
            job.priority = priority
            return s.job_register(job)["eval_id"]

        def completed(eval_ids) -> int:
            n = 0
            for eid in eval_ids:
                ev = st.eval_by_id(eid)
                if ev is not None and ev.status == "complete":
                    n += 1
            return n

        def drain(timeout: float = 120.0) -> None:
            deadline = time.time() + timeout
            while time.time() < deadline:
                stats = s.eval_broker.stats
                if stats["total_ready"] - stats["total_failed"] == 0 \
                        and stats["total_unacked"] == 0 \
                        and stats["total_pending"] == 0:
                    return
                time.sleep(0.005)

        # warm the solve artifacts, then measure steady-state PARALLEL
        # throughput: a back-to-back batch the workers drain with no cap
        # pressure (depth stays well under cap/2 — below the saturation
        # line, so no brownout skews the yardstick)
        for i in range(3):
            register(f"ov-warm-{i}", 50)
        drain()
        n_steady = 24
        t0 = time.perf_counter()
        steady_ids = [register(f"ov-steady-{i}", 50)
                      for i in range(n_steady)]
        deadline = time.time() + 120
        while time.time() < deadline and \
                completed(steady_ids) < n_steady:
            time.sleep(0.005)
        steady_s = time.perf_counter() - t0
        steady_eps = completed(steady_ids) / steady_s

        # burst: 10x the steady rate offered over the window (unpaced
        # catch-up when a registration runs long — offered load is the
        # CONTRACT, the sim must not silently under-offer)
        shed0 = metrics.counter("nomad.broker.shed")
        exp0 = metrics.counter("nomad.worker.eval_expired")
        pexp0 = metrics.counter("nomad.plan.expired")
        trans0 = s.overload.transitions
        offered = max(cap, int(10 * steady_eps * burst_window_s))
        gap = burst_window_s / offered
        burst_ids = []
        reg_at = {}
        max_depth = 0
        over_cap = 0
        t_burst = time.perf_counter()
        for i in range(offered):
            eid = register(f"ov-burst-{i}", 20 + (i % 5) * 15)
            burst_ids.append(eid)
            reg_at[eid] = time.time()
            s.overload.tick()
            depth = s.eval_broker.depth()
            max_depth = max(max_depth, depth)
            if depth > cap:
                over_cap += 1
            sleep_left = t_burst + (i + 1) * gap - time.perf_counter()
            if sleep_left > 0:
                time.sleep(sleep_left)
        burst_s = time.perf_counter() - t_burst

        # recovery: burst stops; drain the READY backlog (backoff-parked
        # follow-ups are the shed channel, not live load)
        t_rec = time.perf_counter()
        drain(timeout=60)
        recovery_s = time.perf_counter() - t_rec
        s.overload.tick()

        # goodput: burst evals that COMPLETED within their deadline
        # (registration-stamped — eval create_time is only set on the
        # worker update path)
        good = 0
        for eid in burst_ids:
            ev = st.eval_by_id(eid)
            if ev is not None and ev.status == "complete" and \
                    (ev.modify_time_unix - reg_at[eid]) <= deadline_s:
                good += 1
        # audit: no expired eval owns a committed alloc (zero expired
        # evals reach a raft entry)
        expired_committed = 0
        for eid in burst_ids:
            tr = obs_trace.get(eid)
            if tr is not None and tr["status"] == "expired" and \
                    st.allocs_by_eval(eid):
                expired_committed += 1
        return {
            "steady_evals_per_s": round(steady_eps, 2),
            "offered_evals": offered,
            "offered_multiple": 10,
            "goodput_evals_per_s": round(good / burst_s, 2),
            "goodput_evals": good,
            "shed_count": int(metrics.counter("nomad.broker.shed")
                              - shed0),
            "expired_count": int(
                metrics.counter("nomad.worker.eval_expired") - exp0),
            "plan_expired_count": int(
                metrics.counter("nomad.plan.expired") - pexp0),
            "pressure_state_transitions":
                s.overload.transitions - trans0,
            "recovery_s": round(recovery_s, 3),
            "max_broker_depth": max_depth,
            "depth_over_cap_samples": over_cap,
            "broker_depth_cap": cap,
            "eval_deadline_s": deadline_s,
            "expired_committed": expired_committed,
        }
    finally:
        s.shutdown()


STORM_NODES = int(os.environ.get("NOMAD_STORM_NODES", str(N_NODES)))
STORM_JOBS = int(os.environ.get("NOMAD_STORM_JOBS", "12"))
STORM_TASKS_PER_JOB = int(os.environ.get("NOMAD_STORM_TASKS", "400"))
STORM_KILL_FRAC = 0.10
STORM_RATE_CAP = int(os.environ.get("NOMAD_STORM_RATE_CAP", "256"))


def _node_storm_run() -> dict:
    """Node-storm lineage (ISSUE 10): kill 10% of the 10k-node sim AT
    ONCE through the real heartbeat-sweep path on a live Server and
    audit the bounded-cost contract:

      * the status flip lands in ceil(K / rate-cap) BATCH raft entries
        (rate-capped sweeps with carry-over), never K per-node entries;
      * replacement evals dedupe to one per affected job — the flood
        size is recorded against the per-(job, node) counterfactual;
      * the device state cache NEVER reseeds (the taint rides the delta
        journal; `nomad.solver.state_cache.reseeds` delta must be 0);
      * zero node-update evals dead-letter, and detection -> every lost
        alloc replaced on a survivor is the recovery wall time.

    The sweep clock is a ManualClock so mass expiry is commanded, not
    raced; the reaper thread sees frozen time and stays idle. Gated in
    tests/test_bench_regression.py once a BENCH_*.json carries the
    block."""
    import math

    from nomad_tpu.chrono import ManualClock
    from nomad_tpu.metrics import metrics
    from nomad_tpu.server import Server
    from nomad_tpu.server.fsm import BATCH_NODE_UPDATE_STATUS
    from nomad_tpu.structs import (
        NODE_STATUS_DOWN, TRIGGER_NODE_UPDATE, SCHED_ALG_TPU,
        SchedulerConfiguration,
    )

    clock = ManualClock()
    s = Server(num_workers=STREAM_CONCURRENCY, gc_interval=9999)
    s.heartbeats.clock = clock
    s.heartbeats.ttl_spread = 0.0
    s.flap_damper.clock = clock
    s.eval_broker.initial_nack_delay = 0.05
    s.eval_broker.subsequent_nack_delay = 0.2
    st = s.state
    st.set_scheduler_config(1, SchedulerConfiguration(
        scheduler_algorithm=SCHED_ALG_TPU,
        eval_batch_window_ms=STREAM_WINDOW_MS,
        heartbeat_invalidate_rate_cap=STORM_RATE_CAP))
    rng = np.random.default_rng(10)
    node_ids = []
    for i in range(STORM_NODES):
        n = _mk_node(i, rng)
        st.upsert_node(i + 2, n)
        node_ids.append(n.id)
        # the store path skips reset_heartbeat_timer: arm explicitly so
        # the sweep owns every node's deadline
        s.heartbeats.reset_heartbeat_timer(n.id)
    batch_entries = [0]
    raft_apply = s.raft.apply

    def counting_apply(msg_type, payload, **kw):
        if msg_type == BATCH_NODE_UPDATE_STATUS:
            batch_entries[0] += 1
        return raft_apply(msg_type, payload, **kw)

    s.raft.apply = counting_apply
    s.start()
    try:
        jobs = []
        for j in range(STORM_JOBS):
            job = _mk_batch_job(f"storm-{j}", STORM_TASKS_PER_JOB)
            s.job_register(job)
            jobs.append(job)

        def placed() -> int:
            return sum(
                1 for job in jobs
                for a in st.allocs_by_job("default", job.id)
                if a.desired_status == "run" and not a.terminal_status())
        want = STORM_JOBS * STORM_TASKS_PER_JOB
        deadline = time.time() + 300
        while time.time() < deadline and placed() < want:
            time.sleep(0.02)
        if placed() < want:
            raise RuntimeError(f"seed placement stalled at "
                               f"{placed()}/{want}")

        # doom 10% of the fleet, weighted onto LOADED nodes so the kill
        # actually strands work (binpack concentrates placements)
        loaded = sorted({a.node_id for job in jobs
                         for a in st.allocs_by_job("default", job.id)})
        k = max(1, int(STORM_NODES * STORM_KILL_FRAC))
        doomed = loaded[: min(len(loaded), k)]
        if len(doomed) < k:
            spare = [nid for nid in node_ids if nid not in set(doomed)]
            doomed += spare[: k - len(doomed)]
        doomed_set = set(doomed)
        lost_allocs = sum(
            1 for job in jobs for a in st.allocs_by_job("default", job.id)
            if a.node_id in doomed_set and a.desired_status == "run"
            and not a.terminal_status())
        # per-(job, node) counterfactual flood: what the pre-batch path
        # would have enqueued for the same kill
        flood_counterfactual = sum(
            len({a.job_id for a in st.allocs_by_node(nid)
                 if not a.terminal_status()}) for nid in doomed)

        reseeds0 = metrics.counter("nomad.solver.state_cache.reseeds")
        dead0 = metrics.counter("nomad.broker.dead_letter")
        coalesced0 = metrics.counter("nomad.broker.node_update_coalesced")
        carryover0 = metrics.counter("nomad.heartbeat.sweep_carryover")
        evals_before = {e.id for e in st.iter_evals()}

        # mass expiry: survivors heartbeat after the advance, then the
        # commanded sweeps drain the doomed set under the rate cap.
        # (The leader-establish barrier re-armed every node at
        # ttl + failover_grace, so the advance must clear that too.)
        # The background reaper thread is stopped first: production has
        # exactly ONE sweeper, and a second concurrent caller could
        # collect an overlapping expired set and bill an extra batch
        # entry against the ceil(K/cap) budget the gate audits.
        s.heartbeats.stop()
        clock.advance(s.heartbeats.min_ttl + s.heartbeats.failover_grace
                      + 1.0)
        for nid in node_ids:
            if nid not in doomed_set:
                s.node_heartbeat(nid)
        t0 = time.perf_counter()
        sweeps = 0
        while any(st.node_by_id(nid).status != NODE_STATUS_DOWN
                  for nid in doomed):
            s.heartbeats._sweep(clock.time())
            sweeps += 1
            if sweeps > 4 * math.ceil(k / max(1, STORM_RATE_CAP)) + 4:
                raise RuntimeError("storm sweeps not converging")
        detection_s = time.perf_counter() - t0

        def recovered() -> bool:
            for job in jobs:
                live = sum(
                    1 for a in st.allocs_by_job("default", job.id)
                    if a.desired_status == "run"
                    and not a.terminal_status()
                    and a.node_id not in doomed_set)
                if live < STORM_TASKS_PER_JOB:
                    return False
            return True
        deadline = time.time() + 300
        while time.time() < deadline and not recovered():
            time.sleep(0.02)
        recovery_s = time.perf_counter() - t0
        if not recovered():
            raise RuntimeError("storm recovery stalled")

        flood = [e for e in st.iter_evals()
                 if e.id not in evals_before
                 and e.triggered_by == TRIGGER_NODE_UPDATE]
        return {
            "n_nodes": STORM_NODES,
            "nodes_killed": len(doomed),
            "allocs_lost": lost_allocs,
            "rate_cap": STORM_RATE_CAP,
            "raft_invalidation_entries": batch_entries[0],
            "sweeps": sweeps,
            "detection_s": round(detection_s, 3),
            "recovery_s": round(recovery_s, 3),
            "eval_flood_size": len(flood),
            "eval_flood_counterfactual": flood_counterfactual,
            "node_update_coalesced": int(
                metrics.counter("nomad.broker.node_update_coalesced")
                - coalesced0),
            "reseeds_delta": int(
                metrics.counter("nomad.solver.state_cache.reseeds")
                - reseeds0),
            "dead_letter_delta": int(
                metrics.counter("nomad.broker.dead_letter") - dead0),
            "carryover": int(
                metrics.counter("nomad.heartbeat.sweep_carryover")
                - carryover0),
        }
    finally:
        s.shutdown()


CRASH_ENTRIES = int(os.environ.get("NOMAD_CRASH_ENTRIES", "1000"))


def _device_chaos_run() -> dict:
    """Device-chaos lineage (ISSUE 14): kill 1→K of the 8 virtual
    devices mid-stream via `device.lost.d<N>` faults and prove the
    elastic mesh absorbs it — every killed device costs ONE generation
    bump + quarantine, resident state-cache twins evacuate onto the
    survivor mesh, every in-flight solve replays, and ZERO evals are
    lost. The stream is the standard 1k-TASK-eval stream
    (STREAM_EVALS concurrent 1k-task evals — the same workload shape
    `evals_per_sec_1k_stream` measures; NOMAD_CHAOS_EVALS resizes).
    Gated by tests/test_bench_regression.py::test_device_chaos_gate
    once recorded (docs/SHARDED_SOLVE.md)."""
    import jax

    from nomad_tpu import faults
    from nomad_tpu.metrics import metrics
    from nomad_tpu.solver import backend as sbackend
    from nomad_tpu.solver import buckets as sbuckets
    from nomad_tpu.solver import microbatch, sharding, state_cache
    from nomad_tpu.structs import SCHED_ALG_TPU

    n_devices = len(jax.devices())
    kills = [k for k in (1, 2, 4) if k < n_devices]
    n_evals = int(os.environ.get("NOMAD_CHAOS_EVALS", str(STREAM_EVALS)))
    old_floor = sbackend.SHARD_MIN_NODES

    def _reset_world():
        faults.clear()
        sharding.reset()
        sbuckets._reset_shards()
        sbackend.reset()
        state_cache.reset()
        microbatch.reset()

    legs = []
    try:
        for ki, kill in enumerate(kills):
            _reset_world()
            # engage the sharded resident twins at sim scale (the 10k
            # sim's bucket is 16384) so the kills hit real partitioned
            # state, not just solo dispatches
            sbackend.SHARD_MIN_NODES = 8192
            base = dict(metrics.snapshot()["counters"])
            # per-leg evacuation wall = MAX over the leg's evacuation
            # SAMPLES (the `nomad.mesh.evacuation_seconds` gauge is
            # last-write-wins — a leg with several evacuations would
            # report only its final, typically warmest one)
            ev_skip = metrics.sample_count("nomad.mesh.evacuation")
            fsm_c = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=29 + ki)
            # each victim dies ONCE at a staggered dispatch, so the
            # stream sees kill → rebuild → evacuate → replay, then the
            # next victim dies on the already-rebuilt mesh (the stagger
            # is tight enough that ALL K victims die inside the stream)
            faults.install({
                f"device.lost.d{d}": {"mode": "after", "n": 4 + 5 * i,
                                      "times": 1}
                for i, d in enumerate(range(1, kill + 1))})
            t0 = time.perf_counter()
            times = _stream_run(fsm_c, n_evals, STREAM_CONCURRENCY)
            wall = time.perf_counter() - t0
            fired = sum(faults.fired(f"device.lost.d{d}")
                        for d in range(1, kill + 1))
            faults.clear()
            snap = metrics.snapshot()

            def delta(key):
                return int(snap["counters"].get(key, 0) - base.get(key, 0))
            legs.append({
                "killed": kill,
                "loss_faults_fired": fired,
                "evals": n_evals,
                "evals_lost": n_evals - len(times),
                "generation_bumps": sharding.generation(),
                "quarantined": sorted(sharding.quarantined()),
                "replays": delta("nomad.mesh.replays"),
                "device_loss_events": delta("nomad.mesh.device_loss"),
                "evacuations": delta(
                    "nomad.solver.state_cache.evacuations"),
                "evacuation_s": round(metrics.percentile(
                    "nomad.mesh.evacuation", 1.0, skip=ev_skip), 4),
                "stream_wall_s": round(wall, 3),
            })
    finally:
        sbackend.SHARD_MIN_NODES = old_floor
        _reset_world()
    return {
        "devices": n_devices,
        "legs": legs,
        "evals_lost": sum(leg["evals_lost"] for leg in legs),
        "replays": sum(leg["replays"] for leg in legs),
        "generation_bumps": sum(leg["generation_bumps"] for leg in legs),
        "max_evacuation_s": max(
            (leg["evacuation_s"] for leg in legs), default=0.0),
    }


def _fused_stream_run() -> dict:
    """Whole-eval residency lineage (ISSUE 15): STRUCTURAL keys only —
    round-trips-per-eval percentiles over a fused short stream, the
    per-phase dispatch counts, and a fixed-seed fused-vs-unfused
    bit-parity differential (the pod-scale diff's shape). Deliberately
    wall-clock-free: the lineage gates identically on a loaded 1-core
    box and a TPU pod (the >=70 evals/s assertion lives with the
    wall-clock stream keys and only arms on multi-core hardware).
    NOMAD_FUSED_EVALS resizes."""
    from nomad_tpu.metrics import metrics
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.solver import backend, state_cache
    from nomad_tpu.structs import SCHED_ALG_TPU

    n_evals = int(os.environ.get("NOMAD_FUSED_EVALS", "64"))

    # ---- fused short stream: round trips + dispatch counts
    state_cache.reset()
    backend.reset()
    base = dict(metrics.snapshot()["counters"])
    rt_skip = metrics.sample_count("nomad.solver.device_round_trips")
    fsm_f = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=29)
    _stream_run(fsm_f, n_evals, STREAM_CONCURRENCY)

    def delta(key):
        return int(metrics.counter(key) - base.get(key, 0))

    dispatches = {ph: delta(f"nomad.solver.dispatches.{ph}")
                  for ph in ("gather", "solve", "explain", "preempt",
                             "fused")}
    # computed HERE: the parity legs below also dispatch fused programs
    fused_dispatches = delta("nomad.solver.dispatch.fused")

    # ---- fixed-seed bit parity: identical cluster + eval id, only the
    # fused knob differs between legs
    def parity_leg(flag: str):
        saved = os.environ.get("NOMAD_SOLVER_FUSED")
        os.environ["NOMAD_SOLVER_FUSED"] = flag
        state_cache.reset()
        backend.reset()
        try:
            f = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=31,
                          pin_ids="fused-par-")
            p = Planner(RaftLog(f), f.state)
            j = _mk_batch_job("fused-par", 1_000)
            _register(f, j)
            _run_eval(f, p, j, eval_id="fused-par-eval")
            return {(a.name, a.node_id)
                    for a in f.state.allocs_by_job("default",
                                                   "fused-par")}
        finally:
            if saved is None:
                os.environ.pop("NOMAD_SOLVER_FUSED", None)
            else:
                os.environ["NOMAD_SOLVER_FUSED"] = saved
            state_cache.reset()
            backend.reset()

    fused_placed = parity_leg("1")
    classic_placed = parity_leg("0")

    return {
        "evals": n_evals,
        "round_trips_p50": metrics.percentile(
            "nomad.solver.device_round_trips", 0.5, skip=rt_skip),
        "round_trips_p95": metrics.percentile(
            "nomad.solver.device_round_trips", 0.95, skip=rt_skip),
        "fused_dispatches": fused_dispatches,
        "dispatches": dispatches,
        "bit_parity": fused_placed == classic_placed,
        "parity_placed": len(fused_placed),
    }


def _convex_run() -> dict:
    """Global convex placement tier lineage (ISSUE 19): STRUCTURAL keys
    only — round-trips-per-eval over a convex-algorithm short stream
    (the one-dispatch contract: p50 <= 1), iterations-to-convergence,
    and the greedy-vs-convex fragmentation/fairness differential on a
    pinned 10k-node fragmented cluster with a host AllocsFit oracle
    re-walk (feasibility_violations must be 0). Deliberately
    wall-clock-free: the lineage gates identically on a loaded 1-core
    box and a TPU pod. NOMAD_CONVEX_EVALS / NOMAD_CONVEX_NODES
    resize."""
    import jax
    from nomad_tpu.metrics import metrics
    from nomad_tpu.solver import backend, convex, state_cache
    from nomad_tpu.solver.kernels import (
        FIT_EPS, NUM_XR, fill_greedy_binpack,
    )
    from nomad_tpu.structs import SCHED_ALG_CONVEX

    n_evals = int(os.environ.get("NOMAD_CONVEX_EVALS", "32"))

    # ---- convex short stream: the one-dispatch round-trip contract.
    # _stream_run pins its own SCHED_ALG_TPU config (the coalescing
    # window knob rides the same write), so the stream engages convex
    # through the NOMAD_SOLVER_CONVEX=1 force lever — the documented
    # bench-parity override (docs/BACKEND_TIERS.md)
    state_cache.reset()
    backend.reset()
    base = dict(metrics.snapshot()["counters"])
    rt_skip = metrics.sample_count("nomad.solver.device_round_trips")
    saved = os.environ.get("NOMAD_SOLVER_CONVEX")
    os.environ["NOMAD_SOLVER_CONVEX"] = "1"
    try:
        fsm_c = _seed_fsm(N_NODES, SCHED_ALG_CONVEX, seed=37)
        _stream_run(fsm_c, n_evals, STREAM_CONCURRENCY)
    finally:
        if saved is None:
            os.environ.pop("NOMAD_SOLVER_CONVEX", None)
        else:
            os.environ["NOMAD_SOLVER_CONVEX"] = saved
    convex_dispatches = int(
        metrics.counter("nomad.solver.dispatch.convex")
        - base.get("nomad.solver.dispatch.convex", 0))
    stream_iters = int(metrics.snapshot()["gauges"].get(
        "nomad.solver.convex.iterations", 0))

    # ---- pinned 10k-node fragmented-cluster differential. Kernel-level
    # on purpose: it drives the SAME compiled program the placer
    # dispatches, with the cluster shape exactly reproducible (beta-skewed
    # usage: most nodes part-full, a tail nearly exhausted)
    n_nodes = int(os.environ.get("NOMAD_CONVEX_NODES", "10000"))
    rng = np.random.default_rng(1910)
    cap = np.zeros((n_nodes, NUM_XR), np.float32)
    cap[:] = (4_000.0, 8_192.0, 500_000.0, 12_001.0, 10_000.0)
    used = np.zeros_like(cap)
    used[:, 0] = (rng.beta(2, 3, n_nodes) * 3_900).astype(np.float32)
    used[:, 1] = (rng.beta(2, 3, n_nodes) * 8_000).astype(np.float32)
    used[:, 2] = (rng.beta(2, 5, n_nodes) * 400_000).astype(np.float32)
    feasible = rng.random(n_nodes) > 0.05
    coll = rng.integers(0, 4, n_nodes).astype(np.int32)
    ask = np.zeros(NUM_XR, np.float32)
    ask[:3] = (250.0, 512.0, 300.0)
    count = np.int32(3_000)
    fn = jax.jit(lambda *a: convex.convex_eval(*a))
    placed, fit, iters, gap, won = jax.device_get(fn(
        cap, used, np.arange(n_nodes, dtype=np.int32),
        np.ones(n_nodes, bool), ask, count, feasible, np.int32(2 ** 30),
        np.zeros(n_nodes, np.float32), coll, np.zeros(n_nodes, np.int32),
        np.bool_(False), np.int32(200), np.float32(1e-4),
        np.float32(0.05), np.float32(2 ** 30)))
    greedy = np.asarray(jax.device_get(fill_greedy_binpack(
        cap, used, ask, count, feasible, np.int32(2 ** 30))))
    # host AllocsFit oracle re-walk at the applier's epsilon
    post = used + placed[:, None].astype(np.float32) * ask[None, :]
    violations = int((post > cap + FIT_EPS).any(axis=1).sum())
    oc = convex.placement_objective(cap, used, ask, placed, coll,
                                    False, 0.05)
    og = convex.placement_objective(cap, used, ask, greedy, coll,
                                    False, 0.05)
    state_cache.reset()
    backend.reset()
    return {
        "evals": n_evals,
        "round_trips_p50": metrics.percentile(
            "nomad.solver.device_round_trips", 0.5, skip=rt_skip),
        "round_trips_p95": metrics.percentile(
            "nomad.solver.device_round_trips", 0.95, skip=rt_skip),
        "convex_dispatches": convex_dispatches,
        "stream_iterations": stream_iters,
        "n_nodes": n_nodes,
        "placed": int(placed.sum()),
        "greedy_placed": int(greedy.sum()),
        "iterations": int(iters),
        "objective_gap": float(gap),
        "convex_won": bool(won),
        "feasibility_violations": violations,
        # positive deltas == greedy worse on that objective term
        "fragmentation_delta": float(og["fragmentation"]
                                     - oc["fragmentation"]),
        "fairness_delta": float(og["fairness"] - oc["fairness"]),
        "objective_delta": float(og["total"] - oc["total"]),
        "all_fit": bool(fit.all()),
    }


def _read_storm_run() -> dict:
    """Read-path scale-out lineage (ISSUE 16, docs/READ_PATH.md):
    STRUCTURAL keys only — on a 3-server virtual cluster, a read storm
    spread across all servers with `stale=True, max_stale_index=<leader
    index>` must (a) serve a nonzero fraction from followers, (b) honor
    the staleness bound on every read, and (c) return payloads
    bit-identical to the leader's at the same index. Plus an event
    fan-out burst against a slow subscriber (coalescing folds engage,
    latest state per key survives, nobody drops) and the columnar-vs-
    row-wise byte ratio for the stub-shaped list payloads. Deliberately
    wall-clock-free: gates identically on a loaded 1-core box and a
    TPU pod. NOMAD_READ_STORM_{JOBS,READS} resize."""
    from nomad_tpu.api_codec import to_columnar
    from nomad_tpu.metrics import metrics
    from nomad_tpu.rpc.virtual import VirtualNetwork
    from nomad_tpu.server import Server
    from nomad_tpu.server.event_broker import Event, EventBroker

    n_jobs = int(os.environ.get("NOMAD_READ_STORM_JOBS", "32"))
    n_reads = int(os.environ.get("NOMAD_READ_STORM_READS", "120"))

    net = VirtualNetwork(seed=16)
    servers = []
    base = dict(metrics.snapshot()["counters"])
    # all setup inside the try: a failure mid-construction must still
    # shut down started servers or they election-churn through the rest
    # of the bench (same discipline as _election_probe)
    try:
        for i in range(3):
            sv = Server(num_workers=0, gc_interval=9999)
            sv.rpc_listen_virtual(net, f"r{i}")
            servers.append(sv)
        peers = {f"r{i}": sv.rpc_addr for i, sv in enumerate(servers)}
        for i, sv in enumerate(servers):
            sv.enable_raft(f"r{i}", peers, election_timeout=(0.5, 1.0),
                           heartbeat_interval=0.08, seed=16_000 + i)
            sv.start()

        deadline = time.time() + 60.0
        leader = None
        while time.time() < deadline and leader is None:
            led = [sv for sv in servers
                   if sv.raft_node.is_leader() and sv.is_leader]
            leader = led[0] if len(led) == 1 else None
            time.sleep(0.005)
        if leader is None:
            raise RuntimeError("read storm: no leader")

        for i in range(n_jobs):
            leader.job_register(_mk_batch_job(f"storm-{i:03d}", 1))
        bound = leader.state.latest_index()
        deadline = time.time() + 30.0
        while time.time() < deadline and any(
                sv.state.latest_index() < bound for sv in servers):
            time.sleep(0.005)

        # ---- the storm: round-robin across ALL servers, stale reads
        # bounded at the leader's index so every answer is current
        served = {"leader": 0, "follower": 0}
        bound_honored = True
        for i in range(n_reads):
            sv = servers[i % len(servers)]
            out = sv.read_list("jobs", stale=True, max_stale_index=bound,
                               timeout=10.0)
            meta = out["QueryMeta"]
            served["follower" if meta["Stale"] else "leader"] += 1
            bound_honored &= meta["LastIndex"] >= bound

        # ---- differential: follower payloads bit-identical to the
        # leader's at the same index (the staleness contract)
        lead = leader.read_list("jobs")
        lead_js = json.dumps(lead["Items"], sort_keys=True)
        bit_identical = all(
            json.dumps(sv.read_list("jobs", stale=True,
                                    max_stale_index=bound,
                                    timeout=10.0)["Items"],
                       sort_keys=True) == lead_js
            for sv in servers if sv is not leader)

        # ---- fan-out burst: slow subscriber, many updates over few
        # keys — coalescing must fold, latest state per key must
        # survive, and the drop rung must NOT fire
        fanout_keys, fanout_events = 16, 400
        broker = EventBroker(max_pending=64, coalesce_after=4)
        sub = broker.subscribe({"Job": ["*"]})
        expect = {}
        for i in range(fanout_events):
            key = f"k{i % fanout_keys}"
            broker.publish(i + 1, [Event(topic="Job", type="T", key=key,
                                         index=i + 1)])
            expect[key] = i + 1
        got = {}
        while True:
            batch = sub.next_events(timeout=0.05)
            if batch is None:
                break
            for e in batch[1]:
                got[e.key] = e.index

        def delta(key):
            return int(metrics.counter(key) - base.get(key, 0))

        fanout = {
            "events_published": fanout_events,
            "keys": fanout_keys,
            "keys_delivered": sum(1 for k, v in expect.items()
                                  if got.get(k) == v),
            "lost_keys": sum(1 for k, v in expect.items()
                             if got.get(k) != v),
            "coalesced_batches": delta("nomad.event.coalesced_batches"),
            "superseded_events": delta("nomad.event.coalesced_events"),
            "dropped_subscribers": delta("nomad.event.subscriber_dropped"),
        }

        # ---- columnar-vs-row bytes on the real stub rows
        rows = lead["Items"]
        row_bytes = len(json.dumps(rows).encode())
        col_bytes = len(json.dumps(to_columnar(rows)).encode())

        total = max(1, served["leader"] + served["follower"])
        return {
            "jobs_seeded": n_jobs,
            "reads": n_reads,
            "leader_served": served["leader"],
            "follower_served": served["follower"],
            "follower_served_frac": round(served["follower"] / total, 4),
            "max_stale_index_honored": bound_honored,
            "stale_bit_identical": bit_identical,
            "fanout": fanout,
            "columnar": {
                "rows": len(rows),
                "row_bytes": row_bytes,
                "columnar_bytes": col_bytes,
                "ratio": round(col_bytes / max(1, row_bytes), 4),
            },
        }
    finally:
        for sv in servers:
            sv.shutdown()


def _partition_chaos_run() -> dict:
    """Partition-chaos lineage (ISSUE 18, docs/PARTITIONS.md): a seeded
    3-server virtual cluster plus live write/heartbeat clients walk
    leader isolation -> asymmetric drops (including reply loss) -> link
    flaps -> heal, with all protocol TIMING (election timeouts, TTLs,
    retry backoff) on a shared ManualClock pumped at a fixed rate so the
    phase schedule is virtual-time, not wall-clock. STRUCTURAL gates
    only: zero double-applied writes (no dedup token committed twice),
    zero lost acked writes (every ack is in the replicated dedup table),
    zero heartbeat invalidations while the drop phase is live, bounded
    post-heal reconvergence in virtual seconds, and a committed state
    identical to a same-seed run with no faults at all."""
    import tempfile
    from collections import Counter

    from nomad_tpu import faults, mock
    from nomad_tpu.chrono import ManualClock
    from nomad_tpu.client import Client
    from nomad_tpu.metrics import metrics
    from nomad_tpu.rpc.retry import RetryPolicy
    from nomad_tpu.rpc.virtual import VirtualNetwork
    from nomad_tpu.server import Server

    SEED = int(os.environ.get("NOMAD_CHAOS_PARTITION_SEED", "18"))
    # virtual seconds the lossy phase must dwell: longer than a full
    # heartbeat TTL (10-15 virtual s) so "zero invalidations" proves the
    # retry ladder kept TTLs alive, not that the phase was too short
    DROP_DWELL_VS = float(os.environ.get("NOMAD_CHAOS_DROP_DWELL", "18.0"))

    def run_cluster(chaotic: bool) -> dict:
        clock = ManualClock()
        net = VirtualNetwork(seed=SEED, clock=clock)
        servers, stop = [], threading.Event()

        def pump():
            # ~5x real time: fast enough that TTL/backoff sleeps resolve
            # quickly, slow enough that the raft loops' REAL-time
            # cadences (heartbeat sender ~0.08s, reaper sweep 1s) stay
            # far inside the virtual election/TTL windows
            while not stop.is_set():
                clock.advance(0.01)
                time.sleep(0.002)

        pumper = threading.Thread(target=pump, daemon=True,
                                  name="chaos-clock-pump")
        pumper.start()
        hb_thread = None
        try:
            for i in range(3):
                sv = Server(num_workers=0, gc_interval=9999)
                sv.rpc_listen_virtual(net, f"p{i}")
                servers.append(sv)
            peers = {f"p{i}": sv.rpc_addr for i, sv in enumerate(servers)}
            for i, sv in enumerate(servers):
                # election timeout in VIRTUAL seconds: the leader's
                # real-time heartbeat cadence (0.08s real ~ 0.4 virtual)
                # must fit many times inside it
                sv.enable_raft(f"p{i}", peers, election_timeout=(6.0, 12.0),
                               heartbeat_interval=0.08, clock=clock,
                               seed=SEED * 1000 + i)
                sv.heartbeats.clock = clock
                sv.start()

            def stable_leader(pool, timeout=45.0):
                deadline = time.time() + timeout
                while time.time() < deadline:
                    led = [s for s in pool
                           if s.raft_node.is_leader() and s.is_leader]
                    if len(led) == 1:
                        return led[0]
                    time.sleep(0.005)
                raise RuntimeError("partition chaos: no stable leader")

            leader = stable_leader(servers)
            addrs = [sv.rpc_addr for sv in servers]
            base_invalidate = metrics.counter("nomad.heartbeat.invalidate")

            # ---- live clients: two writers (every op flips node status,
            # so each acked write is one raft apply) + one heartbeater
            writers, acked, minted = {}, [], {}
            for w in ("w0", "w1"):
                writers[w] = net.client(
                    addrs, src=w, client_id=w, timeout=10.0,
                    retry=RetryPolicy(max_attempts=6, base_s=0.02,
                                      seed=SEED, clock=clock))
                minted[w] = 0

            def write(w, method, *a):
                # token accounting mirrors RpcClient: one req_id per
                # logical write, acked only when the call returns
                minted[w] += 1
                tok = f"{w}:{minted[w]}"
                writers[w].call_write(method, *a)
                acked.append(tok)

            flips = {"w0": 0, "w1": 0}

            def flip(w):
                flips[w] += 1
                write(w, "Node.UpdateStatus", f"chaos-{w}",
                      "down" if flips[w] % 2 else "ready")

            for w in ("w0", "w1"):
                node = mock.node()
                node.id = f"chaos-{w}"
                write(w, "Node.Register", node)

            hb_rpc = net.client(
                addrs, src="hb0", client_id="hb0", timeout=10.0,
                retry=RetryPolicy(max_attempts=3, base_s=0.02,
                                  seed=SEED + 1, clock=clock))

            class _HbRpc:
                # the Client duck-type over the virtual transport:
                # mutating verbs ride call_write (same dedup token on
                # every retry), reads ride call
                def node_register(self, node):
                    return hb_rpc.call_write("Node.Register", node)

                def node_update_status(self, node_id, status):
                    return hb_rpc.call_write("Node.UpdateStatus",
                                             node_id, status)

                def node_get_client_allocs(self, node_id, min_index=0,
                                           timeout=30.0):
                    return hb_rpc.call_timeout(
                        timeout + 15.0, "Node.GetClientAllocs", node_id,
                        min_index=min_index, timeout=timeout)

            hb_client = Client(_HbRpc(), data_dir=tempfile.mkdtemp(
                prefix="nomad-chaos-hb-"), clock=clock, seed=SEED)
            hb_client.node.id = "chaos-hb0"
            ttl = _HbRpc().node_register(hb_client.node)["heartbeat_ttl"]
            hb_client._heartbeat_ttl = ttl

            def hb_loop():
                # the bench drives the beat cadence on the VIRTUAL clock
                # (Client's own loop waits real time); each beat runs the
                # full _heartbeat_once retry ladder
                while not stop.is_set():
                    hb_client._heartbeat_once()
                    until = clock.monotonic() + hb_client._heartbeat_ttl / 3
                    while not stop.is_set() and clock.monotonic() < until:
                        time.sleep(0.002)

            hb_thread = threading.Thread(target=hb_loop, daemon=True,
                                         name="chaos-hb")
            hb_thread.start()

            def dwell(virtual_s):
                until = clock.monotonic() + virtual_s
                deadline = time.time() + 60.0
                while clock.monotonic() < until and time.time() < deadline:
                    time.sleep(0.002)

            # ---- phase 1: baseline writes on the healthy cluster
            for _ in range(2):
                flip("w0")
                flip("w1")
            dwell(2.0)

            # ---- phase 2: leader isolation; writers fail over
            if chaotic:
                net.isolate(leader.raft_node.node_id)
                stable_leader([s for s in servers if s is not leader])
            flip("w0")
            flip("w1")

            # ---- phase 3: asymmetric drops + seeded reply loss on the
            # client links (request direction via net.drop, reply
            # direction via the recv fault site — the double-apply trap).
            # Writer flips INTERLEAVE with the dwell: a flip rides the
            # heartbeat path server-side, so writing through the loss is
            # also what keeps the writers' TTLs alive — the zero-
            # invalidation gate proves the retry ladder carried every
            # beat, not that the phase was too short to expire one
            hb_base = metrics.counter("nomad.heartbeat.invalidate")
            if chaotic:
                net.heal()
                for src in ("w0", "w1", "hb0"):
                    for i in range(3):
                        net.drop(src, f"p{i}", 0.25)
                faults.install({
                    f"raft.transport.recv.{src}.p{i}":
                        {"mode": "probability", "p": 0.15,
                         "seed": SEED + 7}
                    for src in ("w0", "w1", "hb0") for i in range(3)})
            for _ in range(3):
                flip("w0")
                flip("w1")
                dwell(DROP_DWELL_VS / 3)
            hb_invalidations = int(
                metrics.counter("nomad.heartbeat.invalidate") - hb_base)
            faults.clear()

            # ---- phase 4: flap the w0 links AND isolate one follower
            # (quorum holds at 2/3), so the heal has real catch-up to do
            if chaotic:
                net.heal()
                lagger = next(s for s in servers
                              if not s.raft_node.is_leader())
                for i in range(3):
                    net.flap("w0", f"p{i}", 2.0)
                net.isolate(lagger.raft_node.node_id)
            flip("w0")
            flip("w1")

            # ---- phase 5: heal, measure reconvergence in virtual time
            # (single established leader + every server at one index)
            net.heal()
            heal_t = clock.monotonic()
            deadline = time.time() + 60.0
            reconverged = None
            while time.time() < deadline:
                led = [s for s in servers
                       if s.raft_node.is_leader() and s.is_leader]
                if len(led) == 1 and len({
                        s.state.latest_index() for s in servers}) == 1:
                    reconverged = clock.monotonic() - heal_t
                    break
                time.sleep(0.005)
            leader = stable_leader(servers)

            # ---- phase 6: the healed cluster still commits; let the
            # final flips replicate so the cross-server log audit
            # compares settled logs, not a replication race
            flip("w0")
            flip("w1")
            deadline = time.time() + 30.0
            while time.time() < deadline and len({
                    s.state.latest_index() for s in servers}) != 1:
                time.sleep(0.005)

            # ---- audits on the converged logs
            def tokens(sv):
                return [e.payload["_dedup"] for e in sv.raft_node.log
                        if isinstance(e.payload, dict)
                        and "_dedup" in e.payload]

            toks = tokens(leader)
            writer_acked = [t for t in acked
                            if t.startswith(("w0:", "w1:"))]
            lost = [t for t in writer_acked
                    if leader.state.rpc_dedup_get(t) is None]
            return {
                "lost_tokens": lost,
                "lost_in_log": [t for t in lost if t in toks],
                "hb_invalidations_total": int(
                    metrics.counter("nomad.heartbeat.invalidate")
                    - base_invalidate),
                "acked_writes": len(acked),
                "writer_acked": len(writer_acked),
                "double_applied_writes": sum(
                    c - 1 for c in Counter(toks).values() if c > 1),
                "lost_acked_writes": len(lost),
                "heartbeat_invalidations": hb_invalidations,
                "reconverge_virtual_s": round(reconverged, 3)
                if reconverged is not None else None,
                "reconverged": reconverged is not None,
                "token_logs_identical": len({
                    tuple(tokens(sv)) for sv in servers}) == 1,
                "view": {
                    "nodes": {w: leader.state.node_by_id(
                        f"chaos-{w}").status for w in ("w0", "w1")},
                    "writer_tokens": sorted(
                        t for t in toks if t.startswith(("w0:", "w1:"))),
                },
            }
        finally:
            faults.clear()
            stop.set()
            if hb_thread is not None:
                hb_thread.join(5.0)
            for sv in servers:
                sv.shutdown()
            pumper.join(5.0)

    chaos = run_cluster(chaotic=True)
    oracle = run_cluster(chaotic=False)
    view = chaos.pop("view")
    oracle_view = oracle.pop("view")
    return {
        **chaos,
        "oracle_acked_writes": oracle["acked_writes"],
        # the differential twin of the placement-determinism gate: once
        # healed, the committed writer state (statuses + the exact token
        # set) is bit-identical to the same-seed run with no faults
        "state_identical_to_oracle": view == oracle_view,
    }


def _crash_recovery_run() -> dict:
    """Crash-recovery lineage (ISSUE 13, docs/DURABILITY.md): the raft
    WAL's durability/throughput envelope on this box.

      * raft-apply throughput of a disk-backed sole-voter server at
        each fsync discipline (`always` / `interval` / `never`) — the
        plan stream rides this same append path;
      * restart wall time with a LONG log (replay-bound) vs after
        compaction (snapshot-bound) — the operator's recovery story;
      * zero lost commits: every apply acked under fsync=always is
        present after a restart.

    Gated by tests/test_bench_regression.py::test_crash_recovery_gate
    once a BENCH_*.json carries the block: recovery bounded, zero lost
    commits, and fsync=interval within a documented fraction (>=0.3x)
    of fsync=never."""
    import shutil
    import tempfile

    from nomad_tpu.rpc.virtual import VirtualNetwork
    from nomad_tpu.server import Server
    from nomad_tpu.server.fsm import NODE_REGISTER

    rng = np.random.default_rng(13)

    def _boot(root, net_seed, threshold=1 << 30):
        net = VirtualNetwork(seed=net_seed)
        # num_workers=0: pure consensus/persistence measurement — no
        # scheduler traffic competing for the GIL mid-timing
        s = Server(num_workers=0, gc_interval=9999)
        s.rpc_listen_virtual(net, "s0")
        s.enable_raft("s0", {"s0": s.rpc_addr}, data_dir=root,
                      snapshot_threshold=threshold, seed=1,
                      election_timeout=(0.2, 0.4),
                      heartbeat_interval=0.05)
        s.start()
        deadline = time.time() + 20
        while not s.raft_node.is_leader() and time.time() < deadline:
            time.sleep(0.005)
        assert s.raft_node.is_leader(), "sole voter failed to establish"
        return s

    def _throughput_leg(mode, net_seed):
        root = tempfile.mkdtemp(prefix=f"nomad-crash-{mode}-")
        os.environ["NOMAD_RAFT_FSYNC"] = mode
        try:
            s = _boot(root, net_seed)
            try:
                nodes = [_mk_node(i, rng) for i in range(CRASH_ENTRIES)]
                t0 = time.perf_counter()
                acked = 0
                for n in nodes:
                    s.raft.apply(NODE_REGISTER, {"node": n})
                    acked += 1
                wall = time.perf_counter() - t0
            finally:
                s.shutdown()
            return root, acked, CRASH_ENTRIES / wall
        finally:
            os.environ.pop("NOMAD_RAFT_FSYNC", None)

    _root, _, never_eps = _throughput_leg("never", 101)
    shutil.rmtree(_root, ignore_errors=True)
    _root, _, interval_eps = _throughput_leg("interval", 102)
    shutil.rmtree(_root, ignore_errors=True)
    root, acked, always_eps = _throughput_leg("always", 103)

    # restart with the LONG log: replay-bound recovery
    t0 = time.perf_counter()
    s2 = _boot(root, 104)
    restart_long_s = time.perf_counter() - t0
    frames_long = len(s2.raft_node.log)
    recovered = len(s2.state.nodes)
    lost_commits = max(0, acked - recovered)
    # compact, then restart again: snapshot-bound recovery
    with s2.raft_node._lock:
        s2.raft_node._compact_locked()
    frames_post = len(s2.raft_node.log)
    s2.shutdown()
    t0 = time.perf_counter()
    s3 = _boot(root, 105)
    restart_post_s = time.perf_counter() - t0
    recovered_post = len(s3.state.nodes)
    s3.shutdown()
    shutil.rmtree(root, ignore_errors=True)

    return {
        "entries": CRASH_ENTRIES,
        "fsync_always_entries_per_s": round(always_eps, 1),
        "fsync_interval_entries_per_s": round(interval_eps, 1),
        "fsync_never_entries_per_s": round(never_eps, 1),
        "fsync_interval_vs_never_frac": round(
            interval_eps / never_eps, 3) if never_eps else 0.0,
        "restart_s_long_log": round(restart_long_s, 4),
        "restart_s_post_compaction": round(restart_post_s, 4),
        "log_frames_long": frames_long,
        "log_frames_post_compaction": frames_post,
        "acked_entries": acked,
        "recovered_entries": recovered,
        "recovered_entries_post_compaction": recovered_post,
        "lost_commits": lost_commits,
    }


WRITE_STORM_WRITERS = int(os.environ.get("NOMAD_WRITE_STORM_WRITERS", "16"))
WRITE_STORM_OPS = int(os.environ.get("NOMAD_WRITE_STORM_OPS", "320"))


def _write_storm_run() -> dict:
    """Write-storm lineage (ISSUE 20, docs/DURABILITY.md "Group
    commit"): the raft write path under CONCURRENT load at
    fsync=always — the regime group commit exists for. Records, all
    structural (this container is a 1-core box, so wall-clock keys are
    reported but NOT gated — the note key says so):

      * entries-per-fsync p50/max over the storm window — the
        amortization evidence (16 writers must coalesce, p50 >= 4);
      * fsyncs saved vs the one-fsync-per-entry serial discipline;
      * zero lost commits across a restart — batching must not loosen
        the ack-implies-durable contract;
      * batched-vs-serial parity — the same op multiset driven through
        the knob at 1 (the serial oracle) lands the same FSM content.

    Gated by tests/test_bench_regression.py::test_write_storm_gate
    once a BENCH_*.json carries the block."""
    import shutil
    import tempfile

    from nomad_tpu.rpc.virtual import VirtualNetwork
    from nomad_tpu.server import Server
    from nomad_tpu.server.fsm import NODE_REGISTER

    rng = np.random.default_rng(20)
    writers = WRITE_STORM_WRITERS
    per = max(1, WRITE_STORM_OPS // writers)
    work = [[_mk_node(w * per + i, rng) for i in range(per)]
            for w in range(writers)]

    def _boot(root, net_seed):
        net = VirtualNetwork(seed=net_seed)
        s = Server(num_workers=0, gc_interval=9999)
        s.rpc_listen_virtual(net, "s0")
        s.enable_raft("s0", {"s0": s.rpc_addr}, data_dir=root,
                      snapshot_threshold=1 << 30, seed=1,
                      election_timeout=(0.2, 0.4),
                      heartbeat_interval=0.05)
        s.start()
        deadline = time.time() + 20
        while not s.raft_node.is_leader() and time.time() < deadline:
            time.sleep(0.005)
        assert s.raft_node.is_leader(), "sole voter failed to establish"
        return s

    def _storm_leg(root, net_seed):
        """-> (acked, node_ids, batch_sizes, appends, fsyncs, wall_s)."""
        import sys as _sys
        s = _boot(root, net_seed)
        dur = s.raft_node._durable
        sizes = []
        orig_append = dur.append

        def _recording_append(start_index, entries):
            sizes.append(len(entries))
            return orig_append(start_index, entries)

        dur.append = _recording_append
        appends0, fsyncs0 = dur.appends, dur.fsyncs
        acked, ids = [], []
        # the amortization stats ride the FULL-CONCURRENCY window: once
        # the first writer drains its share, the storm winds down into
        # staggered stragglers committing alone — a finite-workload
        # artifact, not the steady state group commit amortizes
        steady_cut = [None]
        lock = threading.Lock()

        def _writer(nodes):
            for n in nodes:
                try:
                    s.raft.apply(NODE_REGISTER, {"node": n}, timeout=30.0)
                    with lock:
                        acked.append(1)
                        ids.append(n.id)
                except Exception:   # noqa: BLE001 — counted as unacked
                    pass
            with lock:
                if steady_cut[0] is None:
                    steady_cut[0] = len(sizes)

        threads = [threading.Thread(target=_writer, args=(w,))
                   for w in work]
        # 1-core box: the default 5ms GIL switch interval is longer
        # than an entire append+fsync round here, so freshly woken
        # writers cannot re-enqueue before the committer drains again
        # and the storm degenerates toward serial. A sub-ms interval
        # restores the interleaving a multi-core server gets for free.
        switch0 = _sys.getswitchinterval()
        _sys.setswitchinterval(0.0005)
        t0 = time.perf_counter()
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            _sys.setswitchinterval(switch0)
        wall = time.perf_counter() - t0
        appends = dur.appends - appends0
        fsyncs = dur.fsyncs - fsyncs0
        dur.append = orig_append
        s.shutdown()
        steady = sizes[:steady_cut[0]] if steady_cut[0] else sizes
        return len(acked), sorted(ids), sizes, steady, appends, fsyncs, \
            wall

    os.environ["NOMAD_RAFT_FSYNC"] = "always"
    try:
        # leg 1 — batched (the default group-commit knob)
        root = tempfile.mkdtemp(prefix="nomad-write-storm-")
        (acked_b, ids_b, sizes, steady, appends_b,
         fsyncs_b, wall_b) = _storm_leg(root, 201)

        # restart audit: every acked write survives at fsync=always
        s2 = _boot(root, 202)
        recovered = len(s2.state.nodes)
        s2.shutdown()
        shutil.rmtree(root, ignore_errors=True)
        lost = max(0, acked_b - recovered)

        # leg 2 — serial oracle: the knob forced to 1 (one entry per
        # append/fsync), same writers, same op multiset
        os.environ["NOMAD_RAFT_GROUP_COMMIT"] = "1"
        try:
            root_s = tempfile.mkdtemp(prefix="nomad-write-serial-")
            (acked_s, ids_s, sizes_s, _steady_s, appends_s,
             _fs, wall_s) = _storm_leg(root_s, 203)
            shutil.rmtree(root_s, ignore_errors=True)
        finally:
            os.environ.pop("NOMAD_RAFT_GROUP_COMMIT", None)
    finally:
        os.environ.pop("NOMAD_RAFT_FSYNC", None)

    sizes_arr = np.asarray(sizes if sizes else [1])
    steady_arr = np.asarray(steady if steady else [1])
    total_ops = writers * per
    return {
        "writers": writers,
        "ops": total_ops,
        "acked_batched": acked_b,
        "acked_serial": acked_s,
        # percentiles over the full-concurrency (steady-state) window
        "entries_per_fsync_p50": float(np.percentile(steady_arr, 50)),
        "entries_per_fsync_p90": float(np.percentile(steady_arr, 90)),
        "entries_per_fsync_max": int(sizes_arr.max()),
        "steady_windows": len(steady_arr),
        "entries_per_fsync_p50_with_drain": float(
            np.percentile(sizes_arr, 50)),
        "appends_batched": appends_b,
        "appends_serial": appends_s,
        "fsyncs_batched": fsyncs_b,
        "fsyncs_saved": int(sizes_arr.sum() - len(sizes_arr)),
        "serial_max_batch": int(max(sizes_s) if sizes_s else 1),
        "recovered_entries": recovered,
        "lost_commits": lost,
        "serial_parity_ok": bool(ids_b == ids_s),
        # 1-core container: recorded for the curious, NOT gated
        "entries_per_s_batched_ungated": round(total_ops / wall_b, 1)
        if wall_b else 0.0,
        "entries_per_s_serial_ungated": round(total_ops / wall_s, 1)
        if wall_s else 0.0,
        "wallclock_note": "1-core container — throughput keys recorded "
                          "but ungated; the gate rides structural keys",
    }


POD_NODES = int(os.environ.get("NOMAD_POD_NODES", "100000"))
POD_TASKS = int(os.environ.get("NOMAD_POD_TASKS", "1000000"))


def _pod_scale_run(n_nodes: int = 0, n_tasks: int = 0,
                   diff_tasks: int = 0) -> dict:
    """Pod-scale lineage (ISSUE 9): a 100k-node / 1M-task eval through
    the REAL scheduler path with the node axis sharded over the device
    mesh — the regime CvxCluster's 100-1000x headroom lives in, and an
    order of magnitude past the 10k-node sim every earlier lineage runs.
    Plus a sharded-vs-solo differential on pinned node/eval ids (the
    deterministic full-curve regime is order-free, so the contract is
    bit-parity; where cross-shard top-k tie-breaks legitimately differ
    the fallback contract is a rejection-rate delta <= 0.5pt — gated in
    tests/test_bench_regression.py once a BENCH records the block).

    The <2s end-to-end target gates only on real multi-device hardware;
    on the dev CPU mesh the gate checks structure + divergence. Wired
    into the main run on accelerators (or NOMAD_BENCH_POD_SCALE=1);
    standalone via `python bench.py --pod-scale`."""
    import jax
    from nomad_tpu.metrics import metrics
    from nomad_tpu.runtime import tune_gc
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.solver import backend
    from nomad_tpu.structs import SCHED_ALG_TPU

    tune_gc()
    n_nodes = n_nodes or POD_NODES
    n_tasks = n_tasks or POD_TASKS
    # the differential replays the SAME placement problem twice more;
    # 1/5 of the headline ask keeps the solo leg affordable while still
    # exercising the full 100k-node axis on both routes
    diff_tasks = diff_tasks or max(50_000, n_tasks // 5)
    devs = jax.devices()
    platform = devs[0].platform

    def seed_fsm():
        # pinned node ids: the sharded and solo differential legs must
        # see IDENTICAL clusters (node ids key the store's iteration
        # order and the plan's node_allocation map)
        return _seed_fsm(n_nodes, SCHED_ALG_TPU, seed=31, pin_ids="pod-")

    def placed_map(fsm, job_id):
        out: dict[str, int] = {}
        for a in fsm.state.iter_allocs():
            if a.job_id == job_id:
                out[a.node_id] = out.get(a.node_id, 0) + 1
        return out

    t_seed = time.perf_counter()
    fsm = seed_fsm()
    seed_s = time.perf_counter() - t_seed
    planner = Planner(RaftLog(fsm), fsm.state)
    # warm the (bucket, k_max) artifacts on the same cluster: the warm
    # job shares the timed job's regime (m > 3 deterministic, same
    # deepest-derived k_max), so the measured region replays compiled
    # artifacts exactly like a steady-state leader would
    warm_job = _mk_batch_job("pod-warm", max(16_384, n_tasks // 20))
    _register(fsm, warm_job)
    t_warm = time.perf_counter()
    _run_eval(fsm, planner, warm_job, eval_id="pod-warm-eval")
    warm_s = time.perf_counter() - t_warm

    sh0 = metrics.counter("nomad.solver.dispatch.sharded")
    job = _mk_batch_job("pod-batch", n_tasks)
    _register(fsm, job)
    planner.start()
    t0 = time.perf_counter()
    shim, _ = _run_eval(fsm, planner, job, eval_id="pod-eval")
    value = time.perf_counter() - t0
    planner.stop()
    _validate(fsm, "pod-batch", n_tasks)
    # measured, not asserted-then-echoed: the regression gate compares
    # placed == n_tasks, so the recorded value must be the real count
    placed = len(fsm.state.allocs_by_job("default", "pod-batch"))
    rejected, total_nodes = _rejection_stats([shim])
    sharded_dispatches = int(
        metrics.counter("nomad.solver.dispatch.sharded") - sh0)

    # ---- sharded-vs-solo differential: identical cluster, identical
    # eval id (the DET001 per-eval rng), only the forced tier differs
    def diff_leg(tier: str) -> tuple[dict, int]:
        saved = os.environ.get("NOMAD_SOLVER_BACKEND")
        os.environ["NOMAD_SOLVER_BACKEND"] = tier
        backend.reset()
        try:
            f = seed_fsm()
            p = Planner(RaftLog(f), f.state)
            j = _mk_batch_job("pod-diff", diff_tasks)
            _register(f, j)
            shim_d, _ = _run_eval(f, p, j, eval_id="pod-diff-eval")
            rej, _tot = _rejection_stats([shim_d])
            return placed_map(f, "pod-diff"), rej
        finally:
            if saved is None:
                os.environ.pop("NOMAD_SOLVER_BACKEND", None)
            else:
                os.environ["NOMAD_SOLVER_BACKEND"] = saved
            backend.reset()

    divergence = {"diff_tasks": diff_tasks}
    if len(devs) > 1:
        sharded_placed, sharded_rej = diff_leg("sharded")
        solo_placed, solo_rej = diff_leg("xla")
        sh_total = sum(sharded_placed.values())
        so_total = sum(solo_placed.values())
        # rejection rate = instances NOT placed out of the ask, plus the
        # applier's optimistic-concurrency node rejections (0 here: one
        # worker) — the delta contract is <= 0.5pt
        sh_rr = 1.0 - sh_total / diff_tasks
        so_rr = 1.0 - so_total / diff_tasks
        divergence.update({
            "bit_parity": sharded_placed == solo_placed,
            "sharded_placed": sh_total,
            "solo_placed": so_total,
            "sharded_rejection_rate": round(sh_rr, 6),
            "solo_rejection_rate": round(so_rr, 6),
            "rejection_delta_pt": round(abs(sh_rr - so_rr) * 100, 4),
            "plan_nodes_rejected_delta": abs(sharded_rej - solo_rej),
        })
    else:
        divergence["skipped"] = "single device: no sharded leg"

    return {
        "metric": f"pod-scale {n_tasks//1000}k-task eval->plan-applied "
                  f"on {n_nodes//1000}k-node sim ({platform})",
        "value_s": round(value, 4),
        "target_s": 2.0,
        "n_nodes": n_nodes,
        "n_tasks": n_tasks,
        "mesh_shape": {"nodes": len(devs)},
        "platform": platform,
        "placed": placed,
        "plan_nodes_rejected": rejected,
        "plan_nodes_total": total_nodes,
        "sharded_dispatches": sharded_dispatches,
        "seed_s": round(seed_s, 3),
        "warm_s": round(warm_s, 3),
        "sharded_vs_solo_divergence": divergence,
    }


def warm_probe() -> None:
    """Subprocess mode: a RESTARTED scheduler process with the persistent
    compile cache populated (VERDICT r4 #3 done-when: warm jit <2s).
    Reports the restart blackout split into its parts: device attach
    (hardware session, cache-independent), state seeding (the FSM
    restore analog, cache-independent), and the jit warmup itself —
    the only part the compile cache can remove."""
    import random

    import jax
    from nomad_tpu.runtime import enable_compile_cache, tune_gc
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.structs import SCHED_ALG_TPU
    enable_compile_cache()      # NOMAD_COMPILE_CACHE from the parent
    tune_gc()
    random.seed(20260729)
    t0 = time.perf_counter()
    jax.devices()
    attach_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    fsm_w = _seed_fsm(N_NODES, SCHED_ALG_TPU)
    planner_w = Planner(RaftLog(fsm_w), fsm_w.state)
    seed_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _warmup_evals(fsm_w, planner_w)
    jit_s = time.perf_counter() - t0
    # second pass on a fresh cluster = pure steady-state execution; the
    # compile/cache-load overhead of a warm restart is the difference
    fsm_2 = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=7)
    planner_2 = Planner(RaftLog(fsm_2), fsm_2.state)
    t0 = time.perf_counter()
    _warmup_evals(fsm_2, planner_2)
    steady_s = time.perf_counter() - t0
    print(json.dumps({"warm_compile_s": round(max(0.0, jit_s - steady_s),
                                              3),
                      "warm_first_pass_s": round(jit_s, 3),
                      "steady_pass_s": round(steady_s, 3),
                      "device_attach_s": round(attach_s, 3),
                      "state_seed_s": round(seed_s, 3)}))


def failover_probe() -> None:
    """Subprocess mode (--failover-probe, ISSUE 6): the PROMOTION half of
    leader failover — a standby server that already holds the replicated
    10k-node state gains leadership at t=0; measure the recovery barrier
    (`leader_failover_s`) and promotion-to-first-completed-solve
    (`failover_first_solve_s`), per-phase timings included. The env
    decides warm vs cold:

      warm  NOMAD_COMPILE_CACHE set (persistent XLA cache populated by
            the parent run) + the standby twin fed + AOT warmup/tensor
            reseed at establish — what a warm-standby follower pays;
      cold  no compile cache, NOMAD_AOT_WARMUP=0 — a promoted server
            that never pre-warmed, paying compiles as placement blackout.

    The ELECTION half is measured separately in-process (see
    _election_probe): it involves no compile state, so it does not need
    process isolation."""
    import random

    import jax
    from nomad_tpu.runtime import enable_compile_cache, tune_gc
    from nomad_tpu.server import Server
    from nomad_tpu.structs import SCHED_ALG_TPU, SchedulerConfiguration

    tune_gc()
    if os.environ.get("NOMAD_COMPILE_CACHE"):
        enable_compile_cache()
    random.seed(20260803)
    warm = os.environ.get("NOMAD_AOT_WARMUP", "") != "0"
    t0 = time.perf_counter()
    jax.devices()
    attach_s = time.perf_counter() - t0

    s = Server(num_workers=2, gc_interval=9999)
    st = s.state
    st.set_scheduler_config(
        1, SchedulerConfiguration(scheduler_algorithm=SCHED_ALG_TPU))
    rng = np.random.default_rng(42)
    for i in range(N_NODES):
        st.upsert_node(i + 2, _mk_node(i, rng))

    standby = {}
    if warm:
        # the standby phase: exactly what a follower does while
        # following — feed the passive tensor twin from its store and
        # pre-compile the solver grid (server._standby_warmup_loop /
        # fsm.on_plan_apply do this continuously in a live follower)
        t1 = time.perf_counter()
        from nomad_tpu.solver import backend, state_cache
        state_cache.standby_feed(st)
        out = backend.warmup(N_NODES)
        standby = {"standby_warmup_s": round(time.perf_counter() - t1, 3),
                   "standby_artifacts": out.get("artifacts")}

    burst = 2_000
    t0 = time.perf_counter()
    s.start()                   # leadership gained: the barrier runs here
    establish_s = time.perf_counter() - t0
    job = _mk_batch_job("failover-burst", burst)
    s.job_register(job)
    deadline = time.time() + 300
    placed = 0
    while time.time() < deadline:
        placed = len(st.allocs_by_job("default", "failover-burst"))
        if placed >= burst:
            break
        time.sleep(0.005)
    first_solve_s = time.perf_counter() - t0
    detail = {k: round(v, 4) for k, v in s._establish_timings.items()}
    s.shutdown()
    if placed < burst:
        raise RuntimeError(f"failover burst placed {placed}/{burst}")
    print(json.dumps({
        "leader_failover_s": round(establish_s, 3),
        "failover_first_solve_s": round(first_solve_s, 3),
        "device_attach_s": round(attach_s, 3),
        "warm": warm,
        **standby,
        "establish_detail": detail,
    }))


def _election_probe(timeout: float = 60.0) -> float:
    """Crash-to-new-established-leader latency on an in-process 3-server
    virtual-transport cluster (no solver state involved — elections are
    pure control-plane, so in-process measurement is honest)."""
    from nomad_tpu.rpc.virtual import VirtualNetwork
    from nomad_tpu.server import Server

    net = VirtualNetwork(seed=0)
    servers = []
    # the whole setup runs inside the try: a failure mid-construction
    # must still shut down the servers already started, or they keep
    # election-churning (and holding the GIL) through the rest of the
    # bench, skewing every timing that follows
    try:
        for i in range(3):
            sv = Server(num_workers=0, gc_interval=9999)
            sv.rpc_listen_virtual(net, f"b{i}")
            servers.append(sv)
        peers = {f"b{i}": sv.rpc_addr for i, sv in enumerate(servers)}
        for i, sv in enumerate(servers):
            sv.enable_raft(f"b{i}", peers, election_timeout=(0.25, 0.5),
                           heartbeat_interval=0.05, seed=i)
            sv.start()
        def _stable(group):
            led = [sv for sv in group
                   if sv.raft_node.is_leader() and sv.is_leader]
            return led[0] if len(led) == 1 else None

        deadline = time.time() + timeout
        leader = None
        while time.time() < deadline and leader is None:
            leader = _stable(servers)
            time.sleep(0.005)
        if leader is None:
            raise RuntimeError("election probe: no initial leader")
        net.crash(leader.raft_node.node_id)
        t0 = time.perf_counter()
        rest = [sv for sv in servers if sv is not leader]
        deadline = time.time() + timeout
        while time.time() < deadline:
            if _stable(rest) is not None:
                return time.perf_counter() - t0
            time.sleep(0.002)
        raise RuntimeError("election probe: no failover leader")
    finally:
        for sv in servers:
            sv.shutdown()


def _run_failover_probes(cache_dir: str) -> dict:
    """Parent-side driver: election in-process, promotion in children
    (compile caches are process-wide, so warm-vs-cold needs isolation)."""
    import subprocess
    out = {"failover_election_s": -1.0, "leader_failover_s": -1.0,
           "failover_first_solve_s": -1.0,
           "failover_first_solve_cold_s": -1.0, "failover_detail": {}}
    try:
        out["failover_election_s"] = round(_election_probe(), 3)
    except Exception:                   # noqa: BLE001 — probe is optional
        pass

    def _child(env):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--failover-probe"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                return json.loads(line)
        return {}

    try:
        warm = _child(dict(os.environ, NOMAD_COMPILE_CACHE=cache_dir))
        cold_env = dict(os.environ, NOMAD_AOT_WARMUP="0",
                        NOMAD_STANDBY_WARMUP="0")
        cold_env.pop("NOMAD_COMPILE_CACHE", None)
        cold = _child(cold_env)
        out.update({
            "leader_failover_s": warm.get("leader_failover_s", -1.0),
            "failover_first_solve_s":
                warm.get("failover_first_solve_s", -1.0),
            "failover_first_solve_cold_s":
                cold.get("failover_first_solve_s", -1.0),
            "failover_detail": {"warm": warm, "cold": cold},
        })
    except Exception:                   # noqa: BLE001 — probe is optional
        pass
    return out


def _lint_block() -> dict:
    """ISSUE 17: run the two-pass nomadlint analyzer over nomad_tpu/
    in-process and report structural keys only (r08 pattern) — counts
    and the scan wall, never load-sensitive numbers. The regression
    gate asserts zero active findings and scan_seconds < 30."""
    import io

    from nomad_tpu.analysis import all_rules
    from nomad_tpu.analysis.__main__ import main as lint_main
    from nomad_tpu.analysis.core import iter_py_files

    tree = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "nomad_tpu")
    files_scanned = sum(1 for _ in iter_py_files([tree]))
    buf = io.StringIO()
    t0 = time.perf_counter()
    rc = lint_main(["--json", tree], out=buf)
    scan_seconds = time.perf_counter() - t0
    return {
        "active_findings": len(json.loads(buf.getvalue())),
        "exit_status": rc,
        "rules": len(all_rules()),
        "files_scanned": files_scanned,
        "scan_seconds": round(scan_seconds, 3),
    }


def main() -> None:
    import random

    import jax
    from nomad_tpu.runtime import (
        enable_compile_cache, ensure_native, tune_gc,
    )
    from nomad_tpu.server.fsm import RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.structs import SCHED_ALG_TPU

    # the same process-level GC tuning Server.start()/Agent.start() apply —
    # the bench simulates the server loop and must measure what prod runs
    tune_gc()
    # compiled sidecars are built, not committed (ADVICE r4); no-op when current
    ensure_native()
    # persistent compile cache in a FRESH dir: compile_s below stays an
    # honest cold number, and the warm-restart probe at the end re-runs
    # the warmup in a child process against the now-populated cache
    import tempfile
    cache_dir = os.environ.get("NOMAD_COMPILE_CACHE") or tempfile.mkdtemp(
        prefix="nomad-bench-xla-cache-")
    enable_compile_cache(cache_dir)

    # the placer decorrelates concurrent workers via random node shuffles;
    # seed it so the reported rejection rates are reproducible run to run
    random.seed(20260729)
    platform = jax.devices()[0].platform

    compile_s = _warmup_compile()

    # device dispatch floor: one trivial jit round trip. Under the axon
    # tunnel this is ~70ms — the single depth-solve dispatch in the
    # headline pays it once, so (value - dispatch_floor_s) approximates
    # what a co-located chip would measure.
    trivial = jax.jit(lambda x: x + 1)
    np.asarray(trivial(np.zeros((8, 8), np.float32)))
    floors = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(trivial(np.zeros((8, 8), np.float32)))
        floors.append(time.perf_counter() - t0)
    dispatch_floor_s = sorted(floors)[2]

    # measured: fresh cluster, the BASELINE 50k/10k scenario, end to end
    from nomad_tpu.metrics import metrics
    fsm = _seed_fsm(N_NODES, SCHED_ALG_TPU)
    planner = Planner(RaftLog(fsm), fsm.state)
    job = _mk_batch_job("c1m-batch", N_TASKS)
    _register(fsm, job)
    metrics.reset()
    # live applier thread: the pipelined plan lifecycle overlaps chunk
    # N's evaluate+commit with chunk N+1's solve/materialize
    planner.start()
    t0 = time.perf_counter()
    shim, sched = _run_eval(fsm, planner, job)
    value = time.perf_counter() - t0
    planner.stop()
    _validate(fsm, "c1m-batch", N_TASKS)
    rejected, total_nodes = _rejection_stats([shim])
    # per-phase breakdown from the hot-path timers (VERDICT r2 #1/#8;
    # ref nomad/worker.go:461,553 + plan_apply.go:185 metric names)
    phases = {
        "phase_reconcile_s": metrics.timer_sum("nomad.scheduler.reconcile"),
        "phase_solve_s": metrics.timer_sum("nomad.solver.solve"),
        "phase_materialize_s": metrics.timer_sum("nomad.solver.materialize"),
        "phase_plan_evaluate_s": metrics.timer_sum("nomad.plan.evaluate"),
        "phase_fsm_commit_s": metrics.timer_sum("nomad.plan.apply"),
    }
    phases = {k: round(v, 4) for k, v in phases.items()}
    # pipelined lifecycle evidence (ISSUE 1): fraction of host-side work
    # (materialize/ids/commit bookkeeping) that ran while a device solve
    # or an async chunk commit was still in flight
    phase_overlap_fraction = round(
        metrics.ratio("nomad.plan.pipeline.overlap",
                      "nomad.plan.pipeline.host"), 4)
    pipeline_chunks = int(metrics.counter("nomad.plan.pipeline.chunks"))
    batched = metrics.counter("nomad.solver.placements_batched")
    total_pl = metrics.counter("nomad.solver.placements_total")
    kernel = ("place_chunked"
              if metrics.counter("nomad.solver.kernel.place_chunked")
              else "fill_depth"
              if metrics.counter("nomad.solver.kernel.fill_depth")
              else "fill_greedy_binpack")

    # which backend tier actually served the headline solves (VERDICT r4
    # weak #1: routing was correct by construction but unproven in the
    # bench JSON; these are backend.record's counters verbatim)
    def _tier_counters(base: dict = None) -> dict:
        out = {}
        for k, v in metrics.snapshot()["counters"].items():
            if k.startswith("nomad.solver.backend.") or \
                    k.startswith("nomad.solver.kernel."):
                d = v - (base or {}).get(k, 0)
                if d:
                    out[k] = int(d)
        return out
    headline_tiers = _tier_counters()
    accel_fired = any(
        k.startswith("nomad.solver.backend.") and
        k.split(".")[-1] in ("pallas", "sharded", "xla")
        for k in headline_tiers)
    if platform == "tpu":
        # on the real chip the 50k deterministic solve MUST ride an
        # accelerator tier (pallas for dense-K depth; xla for chunked)
        assert accel_fired, f"no accelerator tier fired: {headline_tiers}"

    # host-oracle comparison (same end-to-end path, binpack stack).
    # The host path is linear in placements; timing it at 5k tasks keeps the
    # bench runnable every round — the 50k extrapolation is reported as such.
    host_tasks = 5_000
    fsm_h = _seed_fsm(N_NODES, "binpack")
    planner_h = Planner(RaftLog(fsm_h), fsm_h.state)
    job_h = _mk_batch_job("host-batch", host_tasks)
    _register(fsm_h, job_h)
    t0 = time.perf_counter()
    _run_eval(fsm_h, planner_h, job_h)
    host_5k_s = time.perf_counter() - t0
    _validate(fsm_h, "host-batch", host_tasks)
    # tpu at the same scale for a measured like-for-like ratio
    fsm_t5 = _seed_fsm(N_NODES, SCHED_ALG_TPU)
    planner_t5 = Planner(RaftLog(fsm_t5), fsm_t5.state)
    job_t5 = _mk_batch_job("tpu-5k", host_tasks)
    _register(fsm_t5, job_t5)
    t0 = time.perf_counter()
    _run_eval(fsm_t5, planner_t5, job_t5)
    tpu_5k_s = time.perf_counter() - t0

    # sustained throughput (BASELINE's stated metric shape: "evals/sec +
    # p50 plan-submit latency"): a stream of K separate 1k-task evals
    # through CONCURRENT scheduler workers -> serial applier -> FSM on
    # the warm 10k-node cluster (the per-core worker model, ref
    # nomad/worker.go). Concurrent small solves coalesce in the eval
    # micro-batcher into one padded TPU dispatch per window (ISSUE 1) —
    # K evals share one device round trip instead of paying K of them.
    # Per-eval submit-to-applied is still timed individually for the p50.
    # An unmeasured warm pass on a throwaway cluster compiles the
    # jit(vmap) batched artifact first.
    _stream_run(_seed_fsm(N_NODES, SCHED_ALG_TPU, seed=13), 4,
                STREAM_CONCURRENCY)
    # the timed stream runs TRACED (the production default): the trace
    # store feeds the phase-attribution block below, and the separate
    # untraced run afterwards measures the tracing overhead the
    # regression gate bounds at 5% (ISSUE 7)
    from nomad_tpu.obs import chain_summary, chrome_trace
    from nomad_tpu.obs import trace as obs_trace
    obs_trace.configure(enabled=True, sample_rate=1.0)
    obs_trace.reset()
    stream_eval_ids: list = []
    fsm_s = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=11)
    stream_base = dict(metrics.snapshot()["counters"])
    # window the batch-size percentile to the timed stream, like the
    # counters above — the warm pass's small batches must not bias it
    mb_skip = metrics.sample_count("nomad.solver.microbatch.size")
    # per-phase STREAM percentiles (ISSUE 5 satellite): checkpoint the
    # sample windows so phase_*_p50/p95 cover the timed stream only, not
    # the headline pass — plus the commit-coalescing evidence gauges
    STREAM_PHASES = {
        "reconcile": "nomad.scheduler.reconcile",
        "solve": "nomad.solver.solve",
        "materialize": "nomad.solver.materialize",
        "plan_evaluate": "nomad.plan.evaluate",
        "fsm_commit": "nomad.plan.apply",
    }
    phase_skips = {name: metrics.sample_count(metric)
                   for name, metric in STREAM_PHASES.items()}
    cb_skip = metrics.sample_count("nomad.plan.commit_batch_size")
    qd_skip = metrics.sample_count("nomad.plan.queue_depth")
    qr_skip = metrics.sample_count("nomad.plan.queue_residual")
    t_stream0 = time.perf_counter()
    submit_times = _stream_run(fsm_s, STREAM_EVALS, STREAM_CONCURRENCY,
                               eval_ids=stream_eval_ids)
    stream_s = time.perf_counter() - t_stream0
    submit_times.sort()
    p50_submit = submit_times[len(submit_times) // 2]
    stream_tiers = _tier_counters(stream_base)
    stream_phase_pcts = {}
    for name, metric in STREAM_PHASES.items():
        stream_phase_pcts[f"phase_{name}_p50"] = round(
            metrics.percentile(metric, 0.5, skip=phase_skips[name]), 5)
        stream_phase_pcts[f"phase_{name}_p95"] = round(
            metrics.percentile(metric, 0.95, skip=phase_skips[name]), 5)
    # commit_batch_size_p50 is PLAN-weighted: the batch width the median
    # committed PLAN rode (a 15-wide entry carries 15 plans' worth of
    # weight) — the per-drain median would let a few straggler singles
    # mask that nearly every plan coalesced
    cb_sample = metrics.samples.get("nomad.plan.commit_batch_size")
    cb_vals = sorted(cb_sample.raw_window(cb_skip)) if cb_sample else []
    commit_batch_size_p50 = 0.0
    if cb_vals:
        half = sum(cb_vals) / 2.0
        acc = 0.0
        for v in cb_vals:
            acc += v
            if acc >= half:
                commit_batch_size_p50 = v
                break
    commit_batch_size_p50_commits = metrics.percentile(
        "nomad.plan.commit_batch_size", 0.5, skip=cb_skip)
    plan_queue_depth_p50 = metrics.percentile(
        "nomad.plan.queue_depth", 0.5, skip=qd_skip)
    plan_queue_residual_p50 = metrics.percentile(
        "nomad.plan.queue_residual", 0.5, skip=qr_skip)

    def _pc(name: str) -> int:
        key = f"nomad.plan.{name}"
        return int(metrics.counter(key) - stream_base.get(key, 0))
    plan_coalesce = {
        "commits": _pc("coalesced_commits"),
        "plans": _pc("coalesced_plans"),
        "commit_timeouts": _pc("commit_timeout"),
        "snapshot_shared": int(
            metrics.counter("nomad.state.snapshot_shared")
            - stream_base.get("nomad.state.snapshot_shared", 0)),
    }
    stream_batch_size_p50 = metrics.percentile(
        "nomad.solver.microbatch.size", 0.5, skip=mb_skip)
    stream_microbatch = {
        "dispatches": int(metrics.counter(
            "nomad.solver.microbatch.dispatches")
            - stream_base.get("nomad.solver.microbatch.dispatches", 0)),
        "solo": int(metrics.counter("nomad.solver.microbatch.solo")
                    - stream_base.get("nomad.solver.microbatch.solo", 0)),
    }
    # state-cache effectiveness over the TIMED stream only (ISSUE 4): the
    # steady-state phase must be delta-driven, not rebuild-per-eval
    def _sc(name: str) -> int:
        key = f"nomad.solver.state_cache.{name}"
        return int(metrics.counter(key) - stream_base.get(key, 0))
    sc_hits, sc_misses = _sc("hits"), _sc("misses")
    tensor_cache_hit_rate = (sc_hits / (sc_hits + sc_misses)
                             if sc_hits + sc_misses else 0.0)
    state_cache_counters = {
        k.split("nomad.solver.state_cache.")[-1]: int(v)
        for k, v in metrics.snapshot()["counters"].items()
        if k.startswith("nomad.solver.state_cache.")}

    # ---- trace-derived phase attribution (ISSUE 7): what the flat
    # registry cannot say — per-eval queue waits, fan-in widths, and the
    # share of eval time spent in shared dispatch/commit work — computed
    # from the spans of the timed stream, plus a completeness audit and
    # a validity check of the Chrome trace-event export.
    stream_traces = [t for t in (obs_trace.get(eid)
                                 for eid in stream_eval_ids)
                     if t is not None]
    chains = [chain_summary(t) for t in stream_traces]
    trace_complete_frac = (sum(1 for c in chains if c["complete"])
                           / len(stream_eval_ids)) if stream_eval_ids \
        else 0.0
    linked_ok = [c for c in chains
                 if (c["microbatch_linked"] in (True, None))
                 and (c["commit_linked"] in (True, None))]
    trace_fanin_linked_frac = (len(linked_ok) / len(chains)) \
        if chains else 0.0

    def _span_p(name, q):
        vals = sorted(sp["dur"] for t in stream_traces
                      for sp in t["spans"] if sp["name"] == name)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    # NOTE: solver.dispatch.batch spans wrap the WHOLE microbatch.solve
    # call (enqueue + coalescing-window wait) per lane — the actual
    # device time is the ONE shared solver.microbatch.dispatch span, so
    # counting both (or the batch wrappers at all) would inflate
    # dispatch_share ~(K+1)x on a K-lane window
    seen_disp = {}
    for t in stream_traces:
        for sp in list(t["spans"]) + list(t["linked_spans"]):
            if sp["name"] in ("solver.microbatch.dispatch",
                              "plan.commit") or \
                    (sp["name"].startswith("solver.dispatch.") and
                     sp["name"] != "solver.dispatch.batch"):
                seen_disp[sp["id"]] = sp
    fanin_widths = sorted(
        sp["attrs"].get("lanes", 0) for sp in seen_disp.values()
        if sp["name"] == "solver.microbatch.dispatch")
    root_total = sum(t["duration_s"] for t in stream_traces) or 1.0
    dispatch_total = sum(
        sp["dur"] for sp in seen_disp.values()
        if sp["name"] != "plan.commit")
    commit_wait_total = sum(
        sp["dur"] for t in stream_traces for sp in t["spans"]
        if sp["name"] == "plan.commit_wait")
    trace_attribution = {
        "queue_wait_p95": round(_span_p("plan.queue_wait", 0.95), 5),
        "broker_wait_p95": round(_span_p("broker.wait", 0.95), 5),
        "fanin_width_p50": fanin_widths[len(fanin_widths) // 2]
        if fanin_widths else 0,
        "dispatch_share": round(dispatch_total / root_total, 4),
        "commit_wait_share": round(commit_wait_total / root_total, 4),
        "traces": len(stream_traces),
    }
    try:
        export = chrome_trace(stream_traces)
        json.dumps(export)
        trace_export = {"valid": True,
                        "events": len(export["traceEvents"])}
    except Exception as e:              # noqa: BLE001 — report, not crash
        trace_export = {"valid": False, "error": repr(e)[:200]}

    # ---- tracing overhead: the SAME workload (identical seed, fresh
    # cluster each run) in an interleaved untraced/traced sandwich
    # (u t u t u t u, half-length legs) — run-order warmth, cluster-
    # layout variance, and shared-box CPU jitter all dwarf the per-span
    # cost, so each traced leg is compared against the MEAN of its two
    # bracketing untraced legs and the reported overhead is the MEDIAN
    # over the traced legs: one slow leg (a noisy neighbour, a GC
    # pause) cannot claim a 20% "overhead" a single 3-leg sandwich
    # would report. The regression gate bounds the enabled-mode cost at
    # <=5% of stream throughput once recorded.
    leg_evals = max(1, STREAM_EVALS // 2)

    def _overhead_run(traced: bool) -> float:
        obs_trace.configure(enabled=traced)
        fsm_o = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=11)
        t0 = time.perf_counter()
        _stream_run(fsm_o, leg_evals, STREAM_CONCURRENCY)
        return leg_evals / (time.perf_counter() - t0)

    legs = [_overhead_run(traced=bool(i % 2)) for i in range(7)]
    obs_trace.configure(enabled=True)
    overheads = sorted(
        max(0.0, 1.0 - legs[i] / ((legs[i - 1] + legs[i + 1]) / 2.0))
        for i in (1, 3, 5))
    tracing_overhead_frac = round(overheads[1], 4)
    evals_per_sec_untraced = (legs[0] + legs[2] + legs[4] + legs[6]) / 4.0

    # ---- explain overhead (ISSUE 11): the SAME interleaved-sandwich
    # method as tracing above — off/on/off/on/off/on/off half-length
    # legs, each on-leg judged against the mean of its bracketing
    # off-legs, the MEDIAN per-leg overhead reported — bounding the
    # attribution byproduct (per-solve fixed-shape reduce + stage-mask
    # bookkeeping) at <=2% of stream throughput once recorded
    # (tests/test_bench_regression.py::test_explain_overhead_gate).
    from nomad_tpu.solver import explain as solver_explain

    # phase DELTAS for records AND errors (the PR-10 node_storm lesson:
    # absolute process-lifetime counters let earlier same-process bench
    # phases contaminate the lineage the gate asserts on)
    ex_records_base = metrics.counter("nomad.solver.explain.records")
    ex_errors_base = metrics.counter("nomad.solver.explain.errors")

    def _explain_leg(on: bool) -> float:
        solver_explain.configure(enabled=on)
        fsm_e = _seed_fsm(N_NODES, SCHED_ALG_TPU, seed=11)
        t0 = time.perf_counter()
        _stream_run(fsm_e, leg_evals, STREAM_CONCURRENCY)
        return leg_evals / (time.perf_counter() - t0)

    ex_legs = [_explain_leg(on=bool(i % 2)) for i in range(7)]
    solver_explain.configure(enabled=None)     # back to config-driven
    ex_overheads = sorted(
        max(0.0, 1.0 - ex_legs[i] / ((ex_legs[i - 1] + ex_legs[i + 1])
                                     / 2.0))
        for i in (1, 3, 5))
    explain_block = {
        "overhead_frac": round(ex_overheads[1], 4),
        "evals_per_sec_explain_off": round(
            (ex_legs[0] + ex_legs[2] + ex_legs[4] + ex_legs[6]) / 4.0, 2),
        "records": int(metrics.counter("nomad.solver.explain.records")
                       - ex_records_base),
        "errors": int(metrics.counter("nomad.solver.explain.errors")
                      - ex_errors_base),
    }
    if platform == "tpu" and STREAM_CONCURRENCY >= 4:
        # the eval stream must be served by coalesced device dispatches
        # (the batch tier), not host-only — a few solo host solves at the
        # stream's ragged edges are expected, host-ONLY is the regression
        # (BENCH_r05: host=16 because the bench never fed the broker
        # in-flight hint; _stream_run now does)
        assert stream_tiers.get("nomad.solver.backend.batch"), \
            f"stream evals never rode the batch tier at concurrency " \
            f"{STREAM_CONCURRENCY}: {stream_tiers}"
        assert stream_microbatch["dispatches"] >= 1, \
            f"no coalesced device dispatch fired: {stream_microbatch}"

    # plan-rejection parity under optimistic concurrency: same-seed
    # apples-to-apples sims (VERDICT r2 weak #7: one fixed seed is not
    # evidence — a second seed is reported for stability)
    rej_tpu, rej_tpu_alloc = _concurrent_rejection_rate(SCHED_ALG_TPU)
    rej_tpu2, _ = _concurrent_rejection_rate(SCHED_ALG_TPU, seed=1)
    rej_host, rej_host_alloc = _concurrent_rejection_rate("binpack")

    # warm-restart probe (VERDICT r4 #3): a CHILD process re-runs the
    # full warmup against the compile cache this process just populated
    # — the placement blackout a real scheduler restart would pay
    import subprocess
    warm_compile_s = -1.0
    warm_extra = {}
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--warm-probe"],
            env=dict(os.environ, NOMAD_COMPILE_CACHE=cache_dir),
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                warm_extra = json.loads(line)
                warm_compile_s = warm_extra.get("warm_compile_s", -1.0)
    except Exception:                   # noqa: BLE001 — probe is optional
        pass

    # overload lineage (ISSUE 8): 10x burst through a real server —
    # goodput under shedding + deadline enforcement + recovery time,
    # gated by tests/test_bench_regression.py once recorded
    try:
        overload = _overload_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        overload = {"error": repr(e)[:200]}

    # node-storm lineage (ISSUE 10): kill 10% of the sim at once through
    # the real sweep path — batched invalidation entries, eval-flood
    # size vs counterfactual, zero reseeds, recovery wall; gated by
    # tests/test_bench_regression.py once recorded
    try:
        node_storm = _node_storm_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        node_storm = {"error": repr(e)[:200]}

    # crash-recovery lineage (ISSUE 13): fsync-discipline throughput
    # envelope + replay-vs-snapshot restart wall + zero-lost-commit
    # audit; gated by tests/test_bench_regression.py once recorded
    try:
        crash_recovery = _crash_recovery_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        crash_recovery = {"error": repr(e)[:200]}

    # device-chaos lineage (ISSUE 14): kill 1→K of the 8 virtual devices
    # mid-stream — generation bumps, evacuation wall, replayed evals,
    # evals lost == 0; gated once recorded
    try:
        device_chaos = _device_chaos_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        device_chaos = {"error": repr(e)[:200]}

    # whole-eval-residency lineage (ISSUE 15): fused round-trips-per-eval
    # + fused-vs-unfused bit parity, structural keys only; gated once
    # recorded
    try:
        fused_stream = _fused_stream_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        fused_stream = {"error": repr(e)[:200]}

    # convex placement tier lineage (ISSUE 19): one-dispatch round trips
    # under the convex algorithm + the greedy-vs-convex
    # fragmentation/fairness differential on the pinned 10k-node
    # fragmented cluster, structural keys only; gated once recorded
    try:
        convex_tier = _convex_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        convex_tier = {"error": repr(e)[:200]}

    # read-path lineage (ISSUE 16): follower-served stale reads +
    # bit-identity differential + coalescing fan-out zero-loss +
    # columnar byte ratio, structural keys only; gated once recorded
    try:
        read_storm = _read_storm_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        read_storm = {"error": repr(e)[:200]}

    # partition-chaos lineage (ISSUE 18): seeded isolation/drop/flap/heal
    # phases on a ManualClock — exactly-once writes through reply loss,
    # live TTLs through the drop phase, bounded reconvergence, and the
    # faulty-vs-clean same-seed state differential; gated once recorded
    try:
        partition_chaos = _partition_chaos_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        partition_chaos = {"error": repr(e)[:200]}

    # write-storm lineage (ISSUE 20): concurrent raft writers at
    # fsync=always — group-commit amortization (entries per fsync),
    # zero lost commits across restart, batched-vs-serial parity;
    # gated by tests/test_bench_regression.py once recorded
    try:
        write_storm = _write_storm_run()
    except Exception as e:              # noqa: BLE001 — probe is optional
        write_storm = {"error": repr(e)[:200]}

    # leader-failover lineage (ISSUE 6): election latency + warm-standby
    # vs cold promotion-to-first-solve, gated by
    # tests/test_bench_regression.py once recorded
    failover = _run_failover_probes(cache_dir)

    # pod-scale lineage (ISSUE 9): 100k nodes / 1M tasks over the device
    # mesh + the sharded-vs-solo differential. Minutes of wall on a CPU
    # dev box, so the main run includes it on accelerators (or when
    # forced); `python bench.py --pod-scale` runs it standalone.
    pod_scale = None
    want_pod = os.environ.get("NOMAD_BENCH_POD_SCALE", "")
    if want_pod == "1" or (want_pod != "0" and platform != "cpu"):
        try:
            pod_scale = _pod_scale_run()
        except Exception as e:          # noqa: BLE001 — probe is optional
            pod_scale = {"error": repr(e)[:200]}

    # ISSUE 17: whole-program nomadlint lineage — a recorded run proves
    # the tree was finding-free at bench time and the two-pass scan
    # stayed inside tier-1's budget
    try:
        lint = _lint_block()
    except Exception as e:              # noqa: BLE001 — probe is optional
        lint = {"error": repr(e)[:200]}

    print(json.dumps({
        "metric": f"end-to-end {N_TASKS//1000}k-task batch eval->plan-applied"
                  f" on {N_NODES//1000}k-node sim ({platform})",
        "value": round(value, 6),
        "unit": "s",
        "vs_baseline": round(TARGET_S / value, 2),
        "compile_s": round(compile_s, 3),
        "compile_s_warm_restart": warm_compile_s,
        "warm_restart_detail": warm_extra,
        **failover,
        "dispatch_floor_s": round(dispatch_floor_s, 4),
        "placed": N_TASKS,
        "plan_nodes_rejected": rejected,
        "plan_nodes_total": total_nodes,
        "host_binpack_5k_tasks_s": round(host_5k_s, 4),
        "tpu_5k_tasks_s": round(tpu_5k_s, 4),
        "host_50k_extrapolated_s": round(host_5k_s * N_TASKS / host_tasks, 2),
        "speedup_vs_host_measured_5k": round(host_5k_s / tpu_5k_s, 2),
        "rejection_rate_tpu": round(rej_tpu, 4),
        "rejection_rate_tpu_seed2": round(rej_tpu2, 4),
        "rejection_rate_host_binpack": round(rej_host, 4),
        "rejection_parity": bool(rej_tpu <= rej_host + 0.01),
        "rejection_alloc_rate_tpu": round(rej_tpu_alloc, 4),
        "rejection_alloc_rate_host": round(rej_host_alloc, 4),
        "evals_per_sec_1k_stream": round(STREAM_EVALS / stream_s, 2),
        "p50_plan_submit_s": round(p50_submit, 4),
        "stream_concurrency": STREAM_CONCURRENCY,
        "stream_batch_size_p50": round(stream_batch_size_p50, 1),
        "stream_microbatch": stream_microbatch,
        # ISSUE 5: commit-coalescing + per-phase stream evidence. The
        # phase percentiles are over the TIMED stream window only (the
        # headline-pass sums stay in phase_*_s below).
        **stream_phase_pcts,
        "commit_batch_size_p50": round(commit_batch_size_p50, 1),
        "commit_batch_size_p50_commits": round(
            commit_batch_size_p50_commits, 1),
        "plan_queue_depth_p50": round(plan_queue_depth_p50, 1),
        "plan_queue_residual_p50": round(plan_queue_residual_p50, 1),
        "plan_coalesce": plan_coalesce,
        # ISSUE 7: trace-derived phase attribution over the timed stream
        # + completeness/fan-in-link audit + export validity + the
        # enabled-vs-disabled throughput cost (gated <=5%)
        "trace_attribution": trace_attribution,
        "trace_complete_frac": round(trace_complete_frac, 4),
        "trace_fanin_linked_frac": round(trace_fanin_linked_frac, 4),
        "trace_export": trace_export,
        "evals_per_sec_1k_stream_untraced": round(
            evals_per_sec_untraced, 2),
        "tracing_overhead_frac": tracing_overhead_frac,
        "explain": explain_block,
        # ISSUE 8: overload/goodput lineage (10x burst, bounded broker,
        # deadline enforcement, pressure transitions, recovery)
        "overload": overload,
        # ISSUE 10: mass node-failure lineage (batched invalidation,
        # taint-riding state cache, deduped eval flood, recovery wall)
        "node_storm": node_storm,
        "crash_recovery": crash_recovery,
        # ISSUE 20: raft write-path group commit (batched fsync windows
        # under 16 concurrent writers; structural keys only)
        "write_storm": write_storm,
        # ISSUE 14: elastic-mesh device-chaos lineage (kill 1..K of 8
        # virtual devices mid-stream; zero evals lost, replays recorded)
        "device_chaos": device_chaos,
        # ISSUE 15: whole-eval residency (fused dispatch) — structural,
        # load-insensitive keys (round trips per eval, bit parity)
        "fused_stream": fused_stream,
        "convex": convex_tier,
        # ISSUE 16: read-path scale-out (follower stale reads, fan-out
        # coalescing zero-loss, columnar list codec byte ratio)
        "read_storm": read_storm,
        # ISSUE 18: partition-tolerant RPC plane (exactly-once writes
        # through reply loss, heartbeats through drops, reconvergence)
        "partition_chaos": partition_chaos,
        # ISSUE 17: whole-program nomadlint (LOCK002/LOCK003/REG001/
        # REG002) — structural keys only, gated by test_lint_gate
        "lint": lint,
        "tensor_cache_hit_rate": round(tensor_cache_hit_rate, 4),
        "state_cache": state_cache_counters,
        **phases,
        "phase_overlap_fraction": phase_overlap_fraction,
        "plan_pipeline_chunks": pipeline_chunks,
        "solver_kernel": kernel,
        "solver_batched_fraction": round(batched / total_pl, 4)
        if total_pl else 1.0,
        "backend_tiers_headline": headline_tiers,
        "backend_tiers_stream": stream_tiers,
        **({"pod_scale": pod_scale} if pod_scale is not None else {}),
        # ISSUE 3 lineage: breaker/demotion/dead-letter counters so a
        # future regression gate can assert a healthy bench run stays
        # chaos-free (all zeros) while chaos runs leave evidence
        "robustness": {
            k: int(v) for k, v in metrics.snapshot()["counters"].items()
            if k.startswith(("nomad.solver.tier_",
                             "nomad.solver.microbatch.fanout",
                             "nomad.broker.dead_letter",
                             "nomad.worker.eval_failures",
                             "nomad.swallowed_errors",
                             "nomad.faults.fired"))},
    }))


# ------------------------------------------------- kernel-only micro configs

def build_cluster(n_nodes: int, seed: int = 42):
    """Synthetic matrices for the kernel-only micro configs."""
    from nomad_tpu.solver import NUM_XR
    rng = np.random.default_rng(seed)
    cap = np.zeros((n_nodes, NUM_XR), np.float32)
    cap[:, 0] = rng.choice([4_000, 8_000, 16_000, 32_000], n_nodes)   # cpu
    cap[:, 1] = rng.choice([8_192, 16_384, 32_768, 65_536], n_nodes)  # mem
    cap[:, 2] = 500_000                                               # disk
    cap[:, 3] = 12_001                                                # ports
    cap[:, 4] = 10_000                                                # mbits
    used = np.zeros_like(cap)
    busy = rng.random(n_nodes) < 0.3
    used[busy, 0] = rng.integers(500, 3_000, busy.sum())
    used[busy, 1] = rng.integers(1_024, 6_000, busy.sum())
    feasible = rng.random(n_nodes) < 0.95
    return cap, used, feasible


def _bench(fn, *host_args, reps: int = 5):
    """Median wall-clock of transfer + solve + readback."""
    import jax.numpy as jnp

    def put():
        return [jnp.asarray(a) if isinstance(a, np.ndarray) else a
                for a in host_args]
    out = fn(*put())
    np.asarray(out)                      # warmup/compile
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*put())
        counts = np.asarray(out)
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), counts


def kernel_only() -> dict:
    """Round-1 style kernel-only solve (transfer + kernel + readback)."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    cap, used, feas = build_cluster(N_NODES)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1], ask[2] = 250.0, 512.0, 300.0
    solve = jax.jit(fill_greedy_binpack)
    value, counts = _bench(solve, cap, used, ask, jnp.int32(N_TASKS), feas)
    assert int(counts.sum()) == N_TASKS
    return {"metric": f"kernel-only {N_TASKS//1000}k/{N_NODES//1000}k "
            f"({jax.devices()[0].platform})",
            "value": round(value, 6), "unit": "s",
            "vs_baseline": round(TARGET_S / value, 2)}


def config2() -> dict:
    """BASELINE config 2: 1k-task batch / 500 sim nodes, cpu+mem."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    cap, used, feas = build_cluster(500)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 100.0, 256.0
    solve = jax.jit(fill_greedy_binpack)
    value, counts = _bench(solve, cap, used, ask, jnp.int32(1_000), feas)
    assert int(counts.sum()) == 1_000
    return {"metric": "cfg2: 1k-task batch / 500 nodes", "value":
            round(value, 6), "unit": "s",
            "vs_baseline": round(1.0 / value, 2)}


def config3() -> dict:
    """BASELINE config 3: 10k-task batch / 2k nodes with spread +
    anti-affinity + distinct_hosts (the interacting-score scan path)."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR
    from nomad_tpu.solver.kernels import place_chunked
    rng = np.random.default_rng(7)
    n_nodes, n_tasks = 2_000, 10_000
    cap, used, feas = build_cluster(n_nodes, seed=7)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 100.0, 128.0
    racks = rng.integers(0, 100, n_nodes)          # spread property: rack
    solve = jax.jit(lambda *a: place_chunked(
        *a, max_per_node=8, max_steps=256)[0])     # distinct-ish cap
    value, counts = _bench(
        solve, cap, used, ask, jnp.int32(n_tasks), feas,
        np.zeros(n_nodes, np.int32), jnp.int32(n_tasks),
        racks.astype(np.int32)[None, :],           # spread_ids [1, N]
        np.pad(np.zeros((1, 100), np.int32),       # spread_counts, -1 pads
               ((0, 0), (0, 28)), constant_values=-1),
        np.full((1, 128), -1.0, np.float32),       # even mode: no targets
        np.zeros(1, np.int32),                     # mode 0 = even
        np.ones(1, np.float32),                    # weights
        np.zeros(n_nodes, np.float32),             # affinity
        np.full((1, n_nodes), -1, np.int32),       # distinct ids (pad)
        np.full((1, 2), -1, np.int32))             # distinct remaining
    assert int(counts.sum()) == n_tasks, f"placed {counts.sum()}"
    assert int(counts.max()) <= 8
    return {"metric": "cfg3: 10k tasks / 2k nodes spread+anti-affinity",
            "value": round(value, 6), "unit": "s",
            "vs_baseline": round(1.0 / value, 2)}


def config4() -> dict:
    """BASELINE config 4: mixed service+batch with device asks +
    preemption on 5k nodes."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    from nomad_tpu.solver.kernels import preempt_top_k
    rng = np.random.default_rng(11)
    n_nodes = 5_000
    cap, used, feas = build_cluster(n_nodes, seed=11)
    batch_ask = np.zeros(NUM_XR, np.float32)
    batch_ask[0], batch_ask[1] = 400.0, 1024.0
    svc_ask = np.zeros(NUM_XR, np.float32)
    svc_ask[0], svc_ask[1] = 2000.0, 4096.0
    # device asks enter the solver as a pre-lowered feasibility mask
    has_device = rng.random(n_nodes) < 0.2

    solve = jax.jit(fill_greedy_binpack)
    preempt = jax.jit(preempt_top_k)

    def run(cap_j, used_j, feas_j, dev_j):
        placed = solve(cap_j, used_j, jnp.asarray(batch_ask),
                       jnp.int32(15_000), feas_j)
        used2 = used_j + placed[:, None] * jnp.asarray(batch_ask)[None, :]
        svc = solve(cap_j, used2, jnp.asarray(svc_ask), jnp.int32(500),
                    feas_j & dev_j)
        victims = jnp.tile(jnp.asarray(batch_ask)[None, :], (64, 1))
        vprio = jnp.full((64,), 50, jnp.int32)
        mask = preempt(victims, vprio, jnp.asarray(svc_ask),
                       cap_j[0] - used2[0], jnp.int32(80))
        return svc + jnp.zeros_like(placed).at[0].set(
            mask.sum().astype(jnp.int32) * 0)
    value, counts = _bench(run, cap, used, feas, has_device)
    assert int(counts.sum()) >= 500
    return {"metric":
            "cfg4: mixed service+batch, device-masked + preemption, "
            "5k nodes",
            "value": round(value, 6), "unit": "s",
            "vs_baseline": round(1.0 / value, 2)}


def config5() -> dict:
    """BASELINE config 5: C2M-style replay — 2M tasks across 10k nodes as
    200 sequential 10k-task evals with running usage. Reports evals/sec."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    n_nodes, evals, tasks_per = 10_000, 200, 10_000
    cap, used, feas = build_cluster(n_nodes)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1] = 1.0, 1.0

    @jax.jit
    def eval_stream(cap_j, used_j, feas_j):
        def one(used_acc, _):
            placed = fill_greedy_binpack(cap_j, used_acc, jnp.asarray(ask),
                                         jnp.int32(tasks_per), feas_j)
            return used_acc + placed[:, None] * jnp.asarray(ask)[None, :], \
                placed.sum()
        _, placed_counts = jax.lax.scan(one, used_j, None, length=evals)
        return placed_counts

    value, counts = _bench(eval_stream, cap, used, feas, reps=3)
    total = int(counts.sum())
    assert total == evals * tasks_per, f"placed {total}"
    return {"metric": "cfg5: C2M-style eval stream, 2M tasks / 10k nodes "
            f"({evals} evals)", "value": round(value, 6), "unit": "s",
            "evals_per_sec": round(evals / value, 1),
            "tasks_per_sec": round(total / value, 0),
            "vs_baseline": round(TARGET_S / value, 2)}


def backend_compare() -> dict:
    """Time the greedy-fill backends (plain XLA vs pallas fused vs
    GSPMD-sharded when devices allow) at production node-axis size —
    the evidence behind the selector thresholds (backend.PALLAS_MIN_NODES
    / backend.SHARD_MIN_NODES in nomad_tpu/solver/backend.py)."""
    import jax
    import jax.numpy as jnp
    from nomad_tpu.solver import NUM_XR, fill_greedy_binpack
    n = 16_384
    cap, used, feas = build_cluster(n)
    ask = np.zeros(NUM_XR, np.float32)
    ask[0], ask[1], ask[2] = 250.0, 512.0, 300.0
    args = (jnp.asarray(cap), jnp.asarray(used), jnp.asarray(ask),
            jnp.int32(50_000), jnp.asarray(feas), jnp.int32(2 ** 30))
    out = {"metric": f"greedy backends, {n//1000}k nodes "
           f"({jax.devices()[0].platform})", "unit": "s"}

    def timeit(fn):
        np.asarray(fn(*args))            # compile
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            ts.append(time.perf_counter() - t0)
        return round(float(np.median(ts)), 6)

    out["xla_s"] = timeit(jax.jit(fill_greedy_binpack))
    if jax.devices()[0].platform == "tpu":
        from nomad_tpu.solver.pallas_kernels import fill_greedy_binpack_fused
        out["pallas_s"] = timeit(fill_greedy_binpack_fused)
        out["pallas_vs_xla"] = round(out["xla_s"] / out["pallas_s"], 2)
    if len(jax.devices()) > 1:
        from nomad_tpu.solver.sharding import make_mesh, sharded_fill_greedy
        out["sharded_s"] = timeit(sharded_fill_greedy(make_mesh()))
        out["sharded_vs_xla"] = round(out["xla_s"] / out["sharded_s"], 2)
    out["value"] = out["xla_s"]
    out["vs_baseline"] = round(TARGET_S / out["xla_s"], 2)
    return out


def config6(snapshot_path: str = "") -> dict:
    """Snapshot-replay bench (VERDICT r3 #10, ref scheduler/benchmarks/
    helpers_test.go:1-17): schedule against ORGANICALLY-shaped state, not
    synthetic uniforms. With a path, an operator snapshot is restored and
    a 5k-task job is placed on top of whatever the snapshot holds; with
    no path, an organic snapshot is synthesized first — 2k nodes filled
    by 40 assorted jobs with churn (stops, failures) through the REAL
    scheduler, snapshotted, restored into a fresh FSM — so the measured
    region always runs over fragmented, non-uniform usage."""
    import random

    from nomad_tpu.runtime import tune_gc
    from nomad_tpu.server.fsm import NomadFSM, RaftLog
    from nomad_tpu.server.plan_apply import Planner
    from nomad_tpu.structs import SCHED_ALG_TPU

    tune_gc()
    random.seed(606)
    rng = np.random.default_rng(606)
    if snapshot_path:
        blob = open(snapshot_path, "rb").read()
        n_jobs = None
    else:
        fsm0 = _seed_fsm(2_000, SCHED_ALG_TPU, seed=606)
        planner0 = Planner(RaftLog(fsm0), fsm0.state)
        jobs = []
        for j in range(40):
            job = _mk_batch_job(f"organic-{j}",
                                int(rng.integers(20, 400)))
            tg = job.task_groups[0]
            tg.tasks[0].resources.cpu = int(rng.choice([50, 150, 400, 900]))
            tg.tasks[0].resources.memory_mb = int(
                rng.choice([64, 256, 512, 1024]))
            _register(fsm0, job)
            _run_eval(fsm0, planner0, job)
            jobs.append(job)
        # churn: stop a third, fail a slice of allocs (fragmentation)
        s = fsm0.state
        for job in jobs[::3]:
            stopped = job.copy()
            stopped.stop = True
            s.upsert_job(s.latest_index() + 1, stopped)
            _run_eval(fsm0, planner0, stopped)
        for a in list(s.iter_allocs())[:: 17]:
            if a.terminal_status():
                continue
            a2 = a.copy()
            a2.client_status = "failed"
            s.upsert_allocs(s.latest_index() + 1, [a2])
        blob = fsm0.snapshot_bytes()
        n_jobs = len(jobs)

    fsm = NomadFSM()
    fsm.restore_bytes(blob)
    planner = Planner(RaftLog(fsm), fsm.state)
    live = [a for a in fsm.state.iter_allocs() if not a.terminal_status()]
    job = _mk_batch_job("replay-target", 5_000)
    _register(fsm, job)
    t0 = time.perf_counter()
    shim, _ = _run_eval(fsm, planner, job)
    wall = time.perf_counter() - t0
    placed = [a for a in fsm.state.iter_allocs()
              if a.job_id == "replay-target"]
    view = fsm.state.usage.view()
    overcommit = bool((view.used > view.cap + 1e-3).any())
    rejected, total = _rejection_stats([shim])
    return {"metric": "config6 snapshot-replay 5k-task eval over organic "
                      "state (restored snapshot)",
            "value": round(wall, 4), "unit": "s",
            "vs_baseline": round(TARGET_S / wall, 2) if wall else 0.0,
            "snapshot_jobs": n_jobs,
            "snapshot_live_allocs": len(live),
            "placed": len(placed), "plan_nodes_rejected": rejected,
            "plan_nodes_total": total, "overcommit": overcommit}


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--backends":
        print(json.dumps(backend_compare()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--config":
        which = sys.argv[2] if len(sys.argv) > 2 else "all"
        fns = {"2": config2, "3": config3, "4": config4, "5": config5,
               "6": config6}
        for key, fn in fns.items():
            if which in (key, "all"):
                print(json.dumps(fn()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--kernel":
        print(json.dumps(kernel_only()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--pod-scale":
        # standalone pod-scale lineage (100k nodes / 1M tasks + the
        # sharded-vs-solo differential); NOMAD_POD_NODES/NOMAD_POD_TASKS
        # resize for dev iteration
        print(json.dumps(_pod_scale_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--overload":
        # standalone overload lineage (the 10x burst probe alone)
        print(json.dumps(_overload_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--node-storm":
        # standalone node-storm lineage (ISSUE 10): 10% mass kill on the
        # 10k-node sim; NOMAD_STORM_{NODES,JOBS,TASKS,RATE_CAP} resize
        print(json.dumps(_node_storm_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--crash-recovery":
        # standalone crash-recovery lineage (ISSUE 13): fsync-mode
        # raft-apply throughput + restart wall pre/post compaction +
        # lost-commit audit; NOMAD_CRASH_ENTRIES resizes
        print(json.dumps(_crash_recovery_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--write-storm":
        # standalone write-storm lineage (ISSUE 20): 16 concurrent raft
        # writers at fsync=always — entries-per-fsync amortization +
        # restart audit + batched-vs-serial parity;
        # NOMAD_WRITE_STORM_{WRITERS,OPS} resize
        print(json.dumps(_write_storm_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--device-chaos":
        # standalone device-chaos lineage (ISSUE 14): kill 1..K of the
        # 8 virtual devices mid-1k-eval-stream; NOMAD_CHAOS_EVALS resizes
        print(json.dumps(_device_chaos_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--fused-stream":
        # standalone whole-eval-residency lineage (ISSUE 15): fused
        # round trips per eval + bit parity; NOMAD_FUSED_EVALS resizes
        print(json.dumps(_fused_stream_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--convex":
        # standalone convex-tier lineage (ISSUE 19): one-dispatch round
        # trips + the greedy-vs-convex differential on the pinned
        # 10k-node fragmented cluster; NOMAD_CONVEX_{EVALS,NODES} resize
        print(json.dumps(_convex_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--read-storm":
        # standalone read-path lineage (ISSUE 16): follower stale reads
        # + fan-out coalescing + columnar byte ratio;
        # NOMAD_READ_STORM_{JOBS,READS} resize
        print(json.dumps(_read_storm_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--partition-chaos":
        # standalone partition-chaos lineage (ISSUE 18): seeded
        # isolation/drop/flap/heal phases on a ManualClock;
        # NOMAD_CHAOS_PARTITION_SEED / NOMAD_CHAOS_DROP_DWELL resize
        print(json.dumps(_partition_chaos_run()))
    elif len(sys.argv) > 1 and sys.argv[1] == "--warm-probe":
        warm_probe()
    elif len(sys.argv) > 1 and sys.argv[1] == "--failover-probe":
        failover_probe()
    elif len(sys.argv) > 1 and sys.argv[1] == "--failover":
        # standalone combined probe (election + warm/cold promotion)
        import tempfile
        cache_dir = os.environ.get("NOMAD_COMPILE_CACHE") or \
            tempfile.mkdtemp(prefix="nomad-failover-xla-cache-")
        print(json.dumps(_run_failover_probes(cache_dir)))
    else:
        main()   # driver contract: exactly one JSON line
