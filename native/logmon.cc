// nomad-logmon — out-of-process task log collector with size rotation
// (behavioral ref client/logmon/logmon.go + lib/fifo: the reference runs
// one logmon subprocess per task, pumping the task's output FIFO into
// size-capped rotated files so the client agent never holds task IO and
// a client restart never loses or blocks task output).
//
// Usage: nomad-logmon <base-path> <max_bytes> <max_files>
//
//   Reads stdin until EOF and writes <base-path> (e.g. web.stdout.log),
//   rotating by rename when the live file exceeds max_bytes:
//       web.stdout.log -> web.stdout.log.1 -> ... -> .<max_files-1>
//   The oldest file past max_files is unlinked. Writers upstream hold
//   the pipe, not the file, so rotation is invisible to the task.
//
// Exit codes: 0 on EOF, 2 on usage error, 3 on unrecoverable IO error.
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <signal.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Rotator {
  std::string base;
  long long max_bytes;
  int max_files;
  int fd = -1;
  long long written = 0;

  bool open_live() {
    fd = ::open(base.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (fd < 0) return false;
    struct stat st {};
    written = (::fstat(fd, &st) == 0) ? st.st_size : 0;
    return true;
  }

  void rotate() {
    ::close(fd);
    fd = -1;
    if (max_files <= 1) {
      // single-file config: truncate-in-place (matches the Python
      // LogRotator's keep=0 behavior) — never grow without bound
      fd = ::open(base.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
      written = 0;
      return;
    }
    // shift: .<n-1> unlinked, .k -> .k+1, live -> .1
    std::string oldest = base + "." + std::to_string(max_files - 1);
    ::unlink(oldest.c_str());
    for (int k = max_files - 2; k >= 1; --k) {
      std::string from = base + "." + std::to_string(k);
      std::string to = base + "." + std::to_string(k + 1);
      ::rename(from.c_str(), to.c_str());  // ENOENT is fine
    }
    std::string first = base + ".1";
    ::rename(base.c_str(), first.c_str());
    open_live();
  }

  bool write_all(const char* buf, ssize_t n) {
    while (n > 0) {
      // rotate BEFORE writing once the cap is reached — covers both a
      // live file already oversized at open (client-restart reattach)
      // and exact capping across large pipe reads
      if (written >= max_bytes) {
        rotate();
        if (fd < 0) return false;
      }
      long long room = max_bytes - written;
      if (room <= 0) {
        // rotate() failed to free the live file (rename target blocked,
        // permissions changed): keep draining stdin anyway — an
        // over-cap file beats a wedged task blocked on a full pipe
        room = max_bytes;
      }
      ssize_t chunk = n < room ? n : static_cast<ssize_t>(room);
      ssize_t w = ::write(fd, buf, static_cast<size_t>(chunk));
      if (w < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      buf += w;
      n -= w;
      written += w;
    }
    return true;
  }
};

}  // namespace

int main(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr,
                 "usage: nomad-logmon <base-path> <max_bytes> <max_files>\n");
    return 2;
  }
  // the task closing its pipe must not kill logmon mid-buffer
  ::signal(SIGPIPE, SIG_IGN);

  Rotator r;
  r.base = argv[1];
  r.max_bytes = std::atoll(argv[2]);
  r.max_files = std::atoi(argv[3]);
  if (r.max_bytes <= 0) r.max_bytes = 10LL * 1024 * 1024;
  if (r.max_files <= 0) r.max_files = 10;
  if (!r.open_live()) {
    std::perror("nomad-logmon: open");
    return 3;
  }

  char buf[64 * 1024];
  for (;;) {
    ssize_t n = ::read(STDIN_FILENO, buf, sizeof buf);
    if (n == 0) break;  // EOF: task closed its end
    if (n < 0) {
      if (errno == EINTR) continue;
      std::perror("nomad-logmon: read");
      return 3;
    }
    if (!r.write_all(buf, n)) {
      std::perror("nomad-logmon: write");
      return 3;
    }
  }
  ::close(r.fd);
  return 0;
}
