/* nomad_allocstamp: batch construction of slots-dataclass instances.
 *
 * The scheduler's materialize phase mints 50k identical-shaped Allocation
 * objects per headline eval (ref nomad/plan_apply.go:204 applyPlan, where
 * the Go reference pays ~nothing because placements are pointers into
 * arena-allocated structs). In CPython the dataclass __init__ costs ~4us
 * per instance (kwarg parsing + 32 interpreted slot stores), which made
 * materialize 40% of the end-to-end wall clock (VERDICT r3 #2).
 *
 * stamp_batch(type, n, shared, varying) -> list[object]
 *   type:    a slots class (every field must be a member descriptor)
 *   shared:  dict field -> value stored on EVERY instance (callers share
 *            immutable-by-convention objects, matching the store's
 *            copy-on-write update discipline)
 *   varying: dict field -> sequence of n per-instance values
 *
 * Each instance is tp_alloc'd and its slots stored through the member
 * descriptors' tp_descr_set resolved ONCE per field — no attribute-name
 * hashing, no interpreter frames in the loop. ~20x the dataclass ctor.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

typedef struct {
    PyObject *descr;          /* member descriptor (owned) */
    descrsetfunc set;         /* resolved tp_descr_set */
    PyObject *value;          /* shared value (owned), or NULL */
    PyObject *seq;            /* PySequence_Fast for varying (owned) */
} FieldSlot;

static int
resolve_field(PyTypeObject *tp, PyObject *name, FieldSlot *slot)
{
    PyObject *descr = PyObject_GetAttr((PyObject *)tp, name);
    if (descr == NULL)
        return -1;
    descrsetfunc set = Py_TYPE(descr)->tp_descr_set;
    if (set == NULL) {
        PyErr_Format(PyExc_TypeError,
                     "field %R of %s is not a data descriptor",
                     name, tp->tp_name);
        Py_DECREF(descr);
        return -1;
    }
    slot->descr = descr;
    slot->set = set;
    return 0;
}

static void
free_slots(FieldSlot *slots, Py_ssize_t count)
{
    for (Py_ssize_t i = 0; i < count; i++) {
        Py_XDECREF(slots[i].descr);
        Py_XDECREF(slots[i].value);
        Py_XDECREF(slots[i].seq);
    }
    PyMem_Free(slots);
}

static PyObject *
stamp_batch(PyObject *Py_UNUSED(self), PyObject *args)
{
    PyObject *type_obj, *shared, *varying;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "OnO!O!:stamp_batch", &type_obj, &n,
                          &PyDict_Type, &shared, &PyDict_Type, &varying))
        return NULL;
    if (!PyType_Check(type_obj)) {
        PyErr_SetString(PyExc_TypeError, "first argument must be a type");
        return NULL;
    }
    if (n < 0) {
        PyErr_SetString(PyExc_ValueError, "n must be >= 0");
        return NULL;
    }
    PyTypeObject *tp = (PyTypeObject *)type_obj;

    Py_ssize_t n_shared = PyDict_Size(shared);
    Py_ssize_t n_vary = PyDict_Size(varying);
    Py_ssize_t total = n_shared + n_vary;
    FieldSlot *slots = PyMem_Calloc((size_t)(total ? total : 1),
                                    sizeof(FieldSlot));
    if (slots == NULL)
        return PyErr_NoMemory();

    Py_ssize_t count = 0, pos = 0;
    PyObject *key, *value;
    while (PyDict_Next(shared, &pos, &key, &value)) {
        if (resolve_field(tp, key, &slots[count]) < 0)
            goto fail;
        slots[count].value = Py_NewRef(value);
        count++;
    }
    Py_ssize_t vary_start = count;
    pos = 0;
    while (PyDict_Next(varying, &pos, &key, &value)) {
        if (resolve_field(tp, key, &slots[count]) < 0)
            goto fail;
        PyObject *seq = PySequence_Fast(
            value, "varying values must be sequences");
        if (seq == NULL) {
            count++;            /* descr owned; let free_slots release it */
            goto fail;
        }
        if (PySequence_Fast_GET_SIZE(seq) < n) {
            PyErr_Format(PyExc_ValueError,
                         "varying field %R has %zd values, need %zd",
                         key, PySequence_Fast_GET_SIZE(seq), n);
            Py_DECREF(seq);
            count++;
            goto fail;
        }
        slots[count].seq = seq;
        count++;
    }

    PyObject *result = PyList_New(n);
    if (result == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *obj = tp->tp_alloc(tp, 0);
        if (obj == NULL)
            goto fail_result;
        for (Py_ssize_t f = 0; f < vary_start; f++) {
            if (slots[f].set(slots[f].descr, obj, slots[f].value) < 0) {
                Py_DECREF(obj);
                goto fail_result;
            }
        }
        for (Py_ssize_t f = vary_start; f < count; f++) {
            PyObject *v = PySequence_Fast_GET_ITEM(slots[f].seq, i);
            if (slots[f].set(slots[f].descr, obj, v) < 0) {
                Py_DECREF(obj);
                goto fail_result;
            }
        }
        PyList_SET_ITEM(result, i, obj);
    }
    free_slots(slots, count);
    return result;

fail_result:
    /* PyList_New fills with NULL; SET_ITEM'd prefix is owned and freed */
    Py_DECREF(result);
fail:
    free_slots(slots, count);
    return NULL;
}

/* format_uuids(entropy_bytes, n) -> list of n UUIDv4-format strings.
 *
 * The mass-placement path mints one id per allocation; the Python
 * formatter (structs/eval.py new_ids) costs ~1.6us/id in string slicing.
 * Here: one caller-supplied urandom buffer (one getrandom syscall), one
 * ASCII PyUnicode per id written directly — ~50ns/id. Byte layout matches
 * the Python formatter exactly: hex digit 12 forced to '4' (version),
 * digit 16 replaced by "89ab"[digit & 3] (variant).
 */
static PyObject *
format_uuids(PyObject *Py_UNUSED(self), PyObject *args)
{
    Py_buffer buf;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "y*n:format_uuids", &buf, &n))
        return NULL;
    if (n < 0 || buf.len < 16 * n) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "need 16 entropy bytes per id");
        return NULL;
    }
    static const char hexd[] = "0123456789abcdef";
    static const char variant[] = "89ab";
    /* hex digit index -> output index (dashes at 8, 13, 18, 23) */
    static const int outpos[32] = {
        0, 1, 2, 3, 4, 5, 6, 7, 9, 10, 11, 12, 14, 15, 16, 17,
        19, 20, 21, 22, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35};
    PyObject *result = PyList_New(n);
    if (result == NULL) {
        PyBuffer_Release(&buf);
        return NULL;
    }
    const unsigned char *base = (const unsigned char *)buf.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *s = PyUnicode_New(36, 127);
        if (s == NULL) {
            Py_DECREF(result);
            PyBuffer_Release(&buf);
            return NULL;
        }
        char *out = (char *)PyUnicode_1BYTE_DATA(s);
        const unsigned char *b = base + 16 * i;
        out[8] = out[13] = out[18] = out[23] = '-';
        for (int d = 0; d < 32; d++) {
            unsigned nib = (d & 1) ? (b[d >> 1] & 0xF) : (b[d >> 1] >> 4);
            out[outpos[d]] = hexd[nib];
        }
        out[14] = '4';                              /* version nibble */
        out[19] = variant[((b[8] >> 4) & 0xF) & 3]; /* variant nibble */
        PyList_SET_ITEM(result, i, s);
    }
    PyBuffer_Release(&buf);
    return result;
}

static PyMethodDef methods[] = {
    {"stamp_batch", stamp_batch, METH_VARARGS,
     "stamp_batch(type, n, shared, varying) -> list of n instances"},
    {"format_uuids", format_uuids, METH_VARARGS,
     "format_uuids(entropy, n) -> list of n uuid4-format strings"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "nomad_allocstamp",
    "Batch slots-object stamping for the scheduler materialize phase",
    -1, methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit_nomad_allocstamp(void)
{
    return PyModule_Create(&moduledef);
}
