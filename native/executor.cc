// nomad-executor: task supervisor subprocess (the C++ analog of the
// reference's re-exec'd executor, ref drivers/shared/executor/executor.go:240
// UniversalExecutor + executor_linux.go).
//
// The client driver launches one executor per task. The executor:
//   * detaches into its own session (clean process-group kill semantics),
//   * applies resource limits (RLIMIT_AS for memory, RLIMIT_NPROC, nice for
//     cpu shares) before exec'ing the task,
//   * redirects stdout/stderr to the task's log files,
//   * supervises the child and writes {exit_code, signal} to a result file
//     the driver polls — surviving driver/client restarts (reattach), and
//   * forwards SIGTERM/SIGINT to the child's process group.
//
// Protocol: argv[1] is a spec file of simple `key=value` lines:
//   command=/bin/sh        (required)
//   arg=-c                 (repeated, in order)
//   arg=echo hi
//   env=KEY=VALUE          (repeated)
//   cwd=/path
//   stdout=/path/out.log
//   stderr=/path/err.log
//   memory_mb=256          (0 = unlimited)
//   cpu_nice=5             (0-19)
//   cpu_shares=500         (cgroup v2 cpu.weight source; 0 = default)
//   cgroup_parent=/sys/fs/cgroup/nomad  (enables cgroup v2 isolation)
//   result=/path/result.json
//   pidfile=/path/executor.pid
//
// Isolation tiers (ref executor_linux.go): when cgroup_parent is given
// and writable, the child runs in its own cgroup v2 leaf with memory.max
// + cpu.weight and is reaped via cgroup.kill (catches daemonized
// grandchildren that escape the process group); otherwise RLIMIT_AS +
// nice is the degraded fallback.
#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <fstream>
#include <string>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

static pid_t g_child = -1;

static void forward_signal(int sig) {
  if (g_child > 0) {
    // negative pid: the whole process group
    kill(-g_child, sig);
  }
}

struct Spec {
  std::string command;
  std::vector<std::string> args;
  std::vector<std::string> env;
  std::string cwd;
  std::string stdout_path;
  std::string stderr_path;
  std::string result_path;
  std::string pid_path;
  std::string cgroup_parent;
  long memory_mb = 0;
  int cpu_nice = 0;
  long cpu_shares = 0;
};

static bool parse_spec(const char *path, Spec *spec) {
  std::ifstream in(path);
  if (!in.is_open()) return false;
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty() || line[0] == '#') continue;
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = line.substr(0, eq);
    std::string val = line.substr(eq + 1);
    if (key == "command") spec->command = val;
    else if (key == "arg") spec->args.push_back(val);
    else if (key == "env") spec->env.push_back(val);
    else if (key == "cwd") spec->cwd = val;
    else if (key == "stdout") spec->stdout_path = val;
    else if (key == "stderr") spec->stderr_path = val;
    else if (key == "result") spec->result_path = val;
    else if (key == "pidfile") spec->pid_path = val;
    else if (key == "memory_mb") spec->memory_mb = atol(val.c_str());
    else if (key == "cpu_nice") spec->cpu_nice = atoi(val.c_str());
    else if (key == "cpu_shares") spec->cpu_shares = atol(val.c_str());
    else if (key == "cgroup_parent") spec->cgroup_parent = val;
  }
  return !spec->command.empty();
}

static void write_result(const Spec &spec, int exit_code, int sig,
                         const char *err) {
  if (spec.result_path.empty()) return;
  std::string tmp = spec.result_path + ".tmp";
  std::ofstream out(tmp);
  out << "{\"exit_code\": " << exit_code << ", \"signal\": " << sig
      << ", \"err\": \"" << (err ? err : "") << "\"}\n";
  out.close();
  rename(tmp.c_str(), spec.result_path.c_str());
}

static int open_log(const std::string &path) {
  if (path.empty()) return open("/dev/null", O_WRONLY);
  return open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
}

// ------------------------------------------------------------- cgroup v2

static bool write_file(const std::string &path, const std::string &val) {
  int fd = open(path.c_str(), O_WRONLY | O_TRUNC);
  if (fd < 0) return false;
  ssize_t n = write(fd, val.c_str(), val.size());
  close(fd);
  return n == static_cast<ssize_t>(val.size());
}

static void cgroup_teardown(const std::string &leaf);

// Create a cgroup v2 leaf for the task; returns its path or "" when
// unavailable (no permission / not cgroup v2 / memory controller not
// grantable while a memory limit is requested) so callers fall back to
// rlimits. (ref executor_linux.go configureCgroups)
static std::string setup_cgroup(const Spec &spec) {
  if (spec.cgroup_parent.empty()) return "";
  // enable the controllers for children (best effort: may already be on,
  // or delegation may forbid it)
  write_file(spec.cgroup_parent + "/cgroup.subtree_control", "+cpu +memory");
  std::string leaf = spec.cgroup_parent + "/task-" +
                     std::to_string(static_cast<long>(getpid()));
  if (mkdir(leaf.c_str(), 0755) != 0 && errno != EEXIST) return "";
  if (spec.memory_mb > 0) {
    // a requested memory limit must actually land: silently running an
    // unconfined task would be fail-open (the child skips RLIMIT_AS
    // whenever a cgroup leaf is in play)
    if (!write_file(leaf + "/memory.max",
                    std::to_string(spec.memory_mb * 1024L * 1024L))) {
      cgroup_teardown(leaf);
      return "";
    }
  }
  if (spec.cpu_shares > 0) {
    // nomad cpu shares (MHz-ish, default 100-4000+) -> cgroup v2 weight
    // [1, 10000], keeping the same relative ratios
    long weight = spec.cpu_shares / 10;
    if (weight < 1) weight = 1;
    if (weight > 10000) weight = 10000;
    write_file(leaf + "/cpu.weight", std::to_string(weight));
  }
  return leaf;
}

static bool cgroup_enter(const std::string &leaf, pid_t pid) {
  return write_file(leaf + "/cgroup.procs", std::to_string(pid));
}

static void cgroup_teardown(const std::string &leaf) {
  if (leaf.empty()) return;
  // cgroup.kill reaps EVERYTHING in the subtree, including daemonized
  // processes that re-parented out of the task's process group
  write_file(leaf + "/cgroup.kill", "1");
  for (int i = 0; i < 50; i++) {
    if (rmdir(leaf.c_str()) == 0) return;
    usleep(10 * 1000);                  // members still exiting
  }
}

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: nomad-executor <spec-file>\n");
    return 2;
  }
  Spec spec;
  if (!parse_spec(argv[1], &spec)) {
    fprintf(stderr, "nomad-executor: bad spec %s\n", argv[1]);
    return 2;
  }

  // our own session: the driver kills the executor's group as one unit
  setsid();

  // cgroup leaf first so the child can be placed in it right after fork
  std::string cgroup_leaf = setup_cgroup(spec);

  // gate pipe: the child must not exec (and so must not spawn anything)
  // until the parent confirms cgroup placement — otherwise an immediate
  // daemonizing task could fork grandchildren into the WRONG cgroup,
  // where neither cgroup.kill nor the process-group kill reaps them
  int gate[2] = {-1, -1};
  if (pipe(gate) != 0) gate[0] = gate[1] = -1;

  g_child = fork();
  if (g_child < 0) {
    write_result(spec, -1, 0, "fork failed");
    return 1;
  }
  if (g_child == 0) {
    // child: new process group so the supervisor can signal the whole tree
    setpgid(0, 0);
    if (gate[0] >= 0) {
      close(gate[1]);
      char ok = 0;
      ssize_t n = read(gate[0], &ok, 1);   // parent: placed (or no cgroup)
      close(gate[0]);
      if (n != 1 || ok != 'g') _exit(125); // parent bailed: don't exec
    }
    if (!spec.cwd.empty() && chdir(spec.cwd.c_str()) != 0) {
      fprintf(stderr, "chdir(%s): %s\n", spec.cwd.c_str(), strerror(errno));
      _exit(127);
    }
    int out_fd = open_log(spec.stdout_path);
    int err_fd = open_log(spec.stderr_path);
    if (out_fd >= 0) dup2(out_fd, STDOUT_FILENO);
    if (err_fd >= 0) dup2(err_fd, STDERR_FILENO);

    // resource isolation (ref executor_linux.go): rlimit+nice is the
    // fallback tier when no cgroup leaf was granted
    if (cgroup_leaf.empty() && spec.memory_mb > 0) {
      struct rlimit rl;
      rl.rlim_cur = rl.rlim_max =
          static_cast<rlim_t>(spec.memory_mb) * 1024 * 1024;
      setrlimit(RLIMIT_AS, &rl);
    }
    if (spec.cpu_nice > 0) {
      if (nice(spec.cpu_nice) == -1 && errno != 0) { /* best effort */ }
    }

    std::vector<char *> cargs;
    cargs.push_back(const_cast<char *>(spec.command.c_str()));
    for (auto &a : spec.args) cargs.push_back(const_cast<char *>(a.c_str()));
    cargs.push_back(nullptr);
    std::vector<char *> cenv;
    for (auto &e : spec.env) cenv.push_back(const_cast<char *>(e.c_str()));
    cenv.push_back(nullptr);
    execve(spec.command.c_str(), cargs.data(), cenv.data());
    fprintf(stderr, "execve(%s): %s\n", spec.command.c_str(),
            strerror(errno));
    _exit(127);
  }
  setpgid(g_child, g_child);
  if (gate[0] >= 0) close(gate[0]);
  if (!cgroup_leaf.empty() && !cgroup_enter(cgroup_leaf, g_child)) {
    // could not place the child: tear the leaf down, rlimits were
    // skipped so fail closed rather than run unconfined over-memory
    cgroup_teardown(cgroup_leaf);
    cgroup_leaf.clear();
    if (spec.memory_mb > 0) {
      if (gate[1] >= 0) close(gate[1]);  // child sees EOF and exits 125
      kill(-g_child, SIGKILL);
      waitpid(g_child, nullptr, 0);
      write_result(spec, -1, 0, "cgroup placement failed");
      return 1;
    }
  }
  if (gate[1] >= 0) {
    // release the child: it is in its final cgroup (or confinement is
    // rlimit-tier and was applied child-side)
    ssize_t w = write(gate[1], "g", 1);
    (void)w;
    close(gate[1]);
  }

  // pidfile: "<executor_pid> <child_pid>" — the driver SIGKILLs the child's
  // group directly if the executor itself is gone
  if (!spec.pid_path.empty()) {
    std::ofstream pf(spec.pid_path);
    pf << getpid() << " " << g_child << "\n";
  }

  // forward every catchable termination-ish signal (a job may configure
  // kill_signal=SIGUSR1/SIGHUP/...)
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = forward_signal;
  int forwarded[] = {SIGTERM, SIGINT, SIGQUIT, SIGHUP, SIGUSR1, SIGUSR2};
  for (int sig : forwarded) sigaction(sig, &sa, nullptr);

  int status = 0;
  while (true) {
    pid_t got = waitpid(g_child, &status, 0);
    if (got == g_child) break;
    if (got < 0 && errno != EINTR) {
      write_result(spec, -1, 0, "waitpid failed");
      return 1;
    }
  }
  int exit_code = WIFEXITED(status) ? WEXITSTATUS(status) : 0;
  int sig = WIFSIGNALED(status) ? WTERMSIG(status) : 0;
  // reap any stragglers: cgroup.kill catches daemonized escapees the
  // process group can't; the group kill is the fallback tier
  kill(-g_child, SIGKILL);
  cgroup_teardown(cgroup_leaf);
  write_result(spec, exit_code, sig, nullptr);
  return 0;
}
