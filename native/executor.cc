// nomad-executor: task supervisor subprocess (the C++ analog of the
// reference's re-exec'd executor, ref drivers/shared/executor/executor.go:240
// UniversalExecutor + executor_linux.go).
//
// The client driver launches one executor per task. The executor:
//   * detaches into its own session (clean process-group kill semantics),
//   * applies resource limits (RLIMIT_AS for memory, RLIMIT_NPROC, nice for
//     cpu shares) before exec'ing the task,
//   * redirects stdout/stderr to the task's log files,
//   * supervises the child and writes {exit_code, signal} to a result file
//     the driver polls — surviving driver/client restarts (reattach), and
//   * forwards SIGTERM/SIGINT to the child's process group.
//
// Protocol: argv[1] is a spec file of simple `key=value` lines:
//   command=/bin/sh        (required)
//   arg=-c                 (repeated, in order)
//   arg=echo hi
//   env=KEY=VALUE          (repeated)
//   cwd=/path
//   stdout=/path/out.log
//   stderr=/path/err.log
//   memory_mb=256          (0 = unlimited)
//   cpu_nice=5             (0-19)
//   result=/path/result.json
//   pidfile=/path/executor.pid
#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <fstream>
#include <string>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

static pid_t g_child = -1;

static void forward_signal(int sig) {
  if (g_child > 0) {
    // negative pid: the whole process group
    kill(-g_child, sig);
  }
}

struct Spec {
  std::string command;
  std::vector<std::string> args;
  std::vector<std::string> env;
  std::string cwd;
  std::string stdout_path;
  std::string stderr_path;
  std::string result_path;
  std::string pid_path;
  long memory_mb = 0;
  int cpu_nice = 0;
};

static bool parse_spec(const char *path, Spec *spec) {
  std::ifstream in(path);
  if (!in.is_open()) return false;
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty() || line[0] == '#') continue;
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = line.substr(0, eq);
    std::string val = line.substr(eq + 1);
    if (key == "command") spec->command = val;
    else if (key == "arg") spec->args.push_back(val);
    else if (key == "env") spec->env.push_back(val);
    else if (key == "cwd") spec->cwd = val;
    else if (key == "stdout") spec->stdout_path = val;
    else if (key == "stderr") spec->stderr_path = val;
    else if (key == "result") spec->result_path = val;
    else if (key == "pidfile") spec->pid_path = val;
    else if (key == "memory_mb") spec->memory_mb = atol(val.c_str());
    else if (key == "cpu_nice") spec->cpu_nice = atoi(val.c_str());
  }
  return !spec->command.empty();
}

static void write_result(const Spec &spec, int exit_code, int sig,
                         const char *err) {
  if (spec.result_path.empty()) return;
  std::string tmp = spec.result_path + ".tmp";
  std::ofstream out(tmp);
  out << "{\"exit_code\": " << exit_code << ", \"signal\": " << sig
      << ", \"err\": \"" << (err ? err : "") << "\"}\n";
  out.close();
  rename(tmp.c_str(), spec.result_path.c_str());
}

static int open_log(const std::string &path) {
  if (path.empty()) return open("/dev/null", O_WRONLY);
  return open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
}

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: nomad-executor <spec-file>\n");
    return 2;
  }
  Spec spec;
  if (!parse_spec(argv[1], &spec)) {
    fprintf(stderr, "nomad-executor: bad spec %s\n", argv[1]);
    return 2;
  }

  // our own session: the driver kills the executor's group as one unit
  setsid();

  g_child = fork();
  if (g_child < 0) {
    write_result(spec, -1, 0, "fork failed");
    return 1;
  }
  if (g_child == 0) {
    // child: new process group so the supervisor can signal the whole tree
    setpgid(0, 0);
    if (!spec.cwd.empty() && chdir(spec.cwd.c_str()) != 0) {
      fprintf(stderr, "chdir(%s): %s\n", spec.cwd.c_str(), strerror(errno));
      _exit(127);
    }
    int out_fd = open_log(spec.stdout_path);
    int err_fd = open_log(spec.stderr_path);
    if (out_fd >= 0) dup2(out_fd, STDOUT_FILENO);
    if (err_fd >= 0) dup2(err_fd, STDERR_FILENO);

    // resource isolation (ref executor_linux.go resource limits; cgroups
    // arrive with the containerized driver)
    if (spec.memory_mb > 0) {
      struct rlimit rl;
      rl.rlim_cur = rl.rlim_max =
          static_cast<rlim_t>(spec.memory_mb) * 1024 * 1024;
      setrlimit(RLIMIT_AS, &rl);
    }
    if (spec.cpu_nice > 0) {
      if (nice(spec.cpu_nice) == -1 && errno != 0) { /* best effort */ }
    }

    std::vector<char *> cargs;
    cargs.push_back(const_cast<char *>(spec.command.c_str()));
    for (auto &a : spec.args) cargs.push_back(const_cast<char *>(a.c_str()));
    cargs.push_back(nullptr);
    std::vector<char *> cenv;
    for (auto &e : spec.env) cenv.push_back(const_cast<char *>(e.c_str()));
    cenv.push_back(nullptr);
    execve(spec.command.c_str(), cargs.data(), cenv.data());
    fprintf(stderr, "execve(%s): %s\n", spec.command.c_str(),
            strerror(errno));
    _exit(127);
  }
  setpgid(g_child, g_child);

  // pidfile: "<executor_pid> <child_pid>" — the driver SIGKILLs the child's
  // group directly if the executor itself is gone
  if (!spec.pid_path.empty()) {
    std::ofstream pf(spec.pid_path);
    pf << getpid() << " " << g_child << "\n";
  }

  // forward every catchable termination-ish signal (a job may configure
  // kill_signal=SIGUSR1/SIGHUP/...)
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = forward_signal;
  int forwarded[] = {SIGTERM, SIGINT, SIGQUIT, SIGHUP, SIGUSR1, SIGUSR2};
  for (int sig : forwarded) sigaction(sig, &sa, nullptr);

  int status = 0;
  while (true) {
    pid_t got = waitpid(g_child, &status, 0);
    if (got == g_child) break;
    if (got < 0 && errno != EINTR) {
      write_result(spec, -1, 0, "waitpid failed");
      return 1;
    }
  }
  int exit_code = WIFEXITED(status) ? WEXITSTATUS(status) : 0;
  int sig = WIFSIGNALED(status) ? WTERMSIG(status) : 0;
  // reap any stragglers in the task's group
  kill(-g_child, SIGKILL);
  write_result(spec, exit_code, sig, nullptr);
  return 0;
}
